"""Deterministic chaos injection — the fault runtime's test harness.

Reference counterpart: the reference never shipped one, and that is the
point — ps-lite reconnect paths, NaN-step handling, and checkpoint
atomicity were exercised only by real outages. Here every fault the
``mx.fault`` runtime defends against can be injected *deterministically*
(seeded, per-site PRNG streams) so the defenses are ordinary unit tests:

- **NaN gradients** (``nan_batch``): the trainer poisons the incoming batch
  with NaNs, which propagates to loss and every gradient — the same
  signature a real fp overflow produces, with no special-cased graph.
- **Dropped / delayed PS connections** (``kv_drop``, ``kv_delay``): the
  kvstore client closes its own socket before a call, forcing the
  reconnect/backoff/resend machinery through its full path.
- **Slow steps** (``slow_step``): the trainer sleeps past the watchdog
  deadline.
- **Serving-tier faults** (``replica_kill``, ``slow_replica``,
  ``corrupt_artifact``): a serve replica dies mid-request (the router must
  fail over with zero lost accepted requests), a replica's request path
  slows past its deadline (hedging/failover territory), or a cached AOT
  artifact is bit-flipped on disk before verification (the prewarm path
  must detect the CRC mismatch and repair, never serve corrupt weights).
  Each works both as a seeded probability knob and as a one-shot armed
  site (``crash=replica_kill`` arms the kill; :func:`armed` consumes
  non-raising sites like ``corrupt_artifact``).
- **Crash points** (``crash("site")``): hard process-death simulation at
  named sites (e.g. ``nd.save`` mid-write, ``checkpoint.finalize`` before
  the atomic rename, ``serve.registry.load`` mid-model-load — the serving
  registry must keep the previous version serving through it) raising
  :class:`ChaosCrash` — the caller's cleanup does NOT run the happy path,
  exactly like SIGKILL for atomicity purposes within one process.

Determinism: each site draws from its own ``RandomState`` seeded by
``(seed, site)``, so outcomes depend only on the seed and the per-site call
count — never on interleaving across sites or threads (a lock guards each
stream). Enable programmatically::

    with mx.fault.inject.chaos(seed=7, nan_prob=1.0):
        trainer.step(x, y)          # this step's batch is poisoned

or for a whole run via ``MXTPU_CHAOS="seed=7,nan_prob=0.01,kv_drop=0.1"``
(parsed by :func:`enable_from_env`, consulted once at first hook hit).
"""
from __future__ import annotations

import os
import threading
import time
import zlib
from typing import Dict, Iterable, Optional

import numpy as onp

from ..base import MXNetError
from ..lockcheck import make_lock

__all__ = ["ChaosMonkey", "ChaosCrash", "chaos", "enable", "disable",
           "active", "enable_from_env", "should", "maybe_delay",
           "maybe_leak", "scale_ramp", "crash", "armed", "poison",
           "note_step", "heartbeat_stalled"]


class ChaosCrash(MXNetError):
    """Raised at an armed crash point — simulates dying at that site."""

    def __init__(self, site: str):
        super().__init__(f"chaos: injected crash at {site!r}")
        self.site = site


class ChaosMonkey:
    """One seeded fault plan. Knobs are per-site probabilities in [0, 1]:

    ``nan_prob``  — ``should('nan_batch')``: poison the next batch
    ``kv_drop``   — ``should('kv_drop')``: drop the PS connection pre-call
    ``slow_prob`` — ``maybe_delay('slow_step')`` sleeps ``delay_s``
    ``kv_delay``  — ``maybe_delay('kv_delay')`` sleeps ``delay_s``
    ``slow_input`` — ``maybe_delay('slow_input')`` sleeps ``delay_s`` in
    the ``io.PrefetchIter`` producer — seeded input starvation, so the
    goodput ledger's ``input_wait`` attribution is testable end to end
    ``replica_kill``     — ``should('replica_kill')``: a serve replica
    dies on its next request (the router's failover path)
    ``slow_replica``     — ``maybe_delay('slow_replica')`` sleeps
    ``delay_s`` in a replica's request path
    ``corrupt_artifact`` — ``should('corrupt_artifact')``: the artifact
    cache bit-flips a cached file before CRC verification
    ``decode_block_exhaustion`` — ``should('decode_block_exhaustion')``:
    the decode block pool raises ``CacheExhausted`` on an allocation —
    the ``DecodeBatcher`` must requeue (bounded) or shed the stream
    loudly, never truncate it silently
    ``decode_replica_death`` — ``should('decode_replica_death')``: the
    decode worker dies mid-generation at a token boundary — every
    in-flight stream must fail with ``ReplicaUnavailable`` after ONE
    flight bundle, never hang
    ``leak`` — ``maybe_leak(site)``: allocate and RETAIN ``leak_bytes``
    of device memory at the site (the trainer's ``trainer.step`` hook) —
    a simulated slow leak the ``telemetry.memory`` watchdog must flag
    as a ``memory.leak`` event
    ``grad_blowup`` / ``activation_drift`` — ``scale_ramp(site)``: a
    seeded per-site MULTIPLICATIVE ramp consumed by ``trainer.step``'s
    chaos batch hook — each fired draw multiplies the site's running
    scale by ``blowup_factor`` (resp. the gentler ``drift_factor``), so
    activations and gradients grow monotonically step over step: the
    slow-divergence signature the ``telemetry.numerics`` drift watchdog
    must flag BEFORE the run goes non-finite (the ramp eventually
    overflows f32 and the classic StepGuard verdict trips too — one
    knob drives the full drift → non-finite escalation timeline)
    ``collective_divergence`` — ``should('collective_divergence')``: the
    collective-schedule ledger perturbs THIS process's fingerprint table
    (salted with its process index) just before a crosscheck exchange —
    the seeded SPMD-divergence drill; any >=2-process crosscheck with the
    draw fired must trip and write a flight bundle
    (``tools/collective_smoke.py`` and the CI crosscheck smoke)
    ``host_kill`` / ``host_stall`` — STEP NUMBERS, not probabilities
    (``-1`` = off, like every other knob's default). At the named
    training step, ``note_step(step)`` (the trainer's chaos hook)
    either SIGKILLs this process (``host_kill`` — the clean corpse: no
    cleanup, no flush, exactly what a preempted TPU host looks like to
    its peers) or stops the elastic heartbeat while the process keeps
    running (``host_stall`` — the nastier failure: the host still
    answers nothing is wrong, only its lease goes stale). Both exist to
    drill ``parallel.elastic``'s lease watchdog: survivors must detect
    the loss by lease expiry and write a flight bundle stamped with the
    dead process index, never hang in a collective.
    ``crash_sites`` — iterable of site names where :meth:`crash` raises
    (and :meth:`armed` consumes without raising); each site fires at most
    ``crash_count`` times (default 1) then disarms, so a retried save can
    succeed after the simulated death.
    """

    def __init__(self, seed: int = 0, nan_prob: float = 0.0,
                 kv_drop: float = 0.0, slow_prob: float = 0.0,
                 kv_delay: float = 0.0, delay_s: float = 0.0,
                 slow_input: float = 0.0,
                 replica_kill: float = 0.0, slow_replica: float = 0.0,
                 corrupt_artifact: float = 0.0,
                 decode_block_exhaustion: float = 0.0,
                 decode_replica_death: float = 0.0,
                 leak: float = 0.0, leak_bytes: float = 1 << 20,
                 collective_divergence: float = 0.0,
                 grad_blowup: float = 0.0, activation_drift: float = 0.0,
                 blowup_factor: float = 16.0, drift_factor: float = 1.5,
                 host_kill: int = -1, host_stall: int = -1,
                 crash_sites: Iterable[str] = (), crash_count: int = 1):
        self.seed = int(seed)
        self.probs: Dict[str, float] = {
            "nan_batch": float(nan_prob), "kv_drop": float(kv_drop),
            "slow_step": float(slow_prob), "kv_delay": float(kv_delay),
            "slow_input": float(slow_input),
            "replica_kill": float(replica_kill),
            "slow_replica": float(slow_replica),
            "corrupt_artifact": float(corrupt_artifact),
            "decode_block_exhaustion": float(decode_block_exhaustion),
            "decode_replica_death": float(decode_replica_death),
            "leak": float(leak),
            "collective_divergence": float(collective_divergence),
            "grad_blowup": float(grad_blowup),
            "activation_drift": float(activation_drift),
        }
        self.leak_bytes = int(leak_bytes)
        #: per-fired-draw ramp factors of the numerics-drift chaos knobs
        self._ramp_factor: Dict[str, float] = {
            "grad_blowup": float(blowup_factor),
            "activation_drift": float(drift_factor)}
        #: fired-draw counts per ramp site (scale = factor ** count)
        self._ramp: Dict[str, int] = {}
        #: retained leak allocations — the whole point is that nothing
        #: ever frees them while the monkey is installed
        self._leaked: list = []
        self.delay_s = float(delay_s)
        #: elastic-drill knobs: step numbers (-1 = off)
        self.host_kill_step = int(host_kill)
        self.host_stall_step = int(host_stall)
        self._last_step: Optional[int] = None
        self._armed: Dict[str, int] = {s: int(crash_count)
                                       for s in crash_sites}
        self._streams: Dict[str, onp.random.RandomState] = {}
        self._lock = make_lock("ChaosMonkey._lock")
        #: injection log: (site, fired) in per-site call order — lets tests
        #: assert exactly which faults a seed produced
        self.log: list = []

    def _stream(self, site: str) -> onp.random.RandomState:
        rs = self._streams.get(site)
        if rs is None:
            rs = onp.random.RandomState(
                (self.seed ^ zlib.crc32(site.encode())) & 0x7FFFFFFF)
            self._streams[site] = rs
        return rs

    def should(self, site: str) -> bool:
        """Draw this site's next fault decision (thread-safe). Fired
        draws publish a ``chaos`` telemetry event carrying the current
        step/request correlation id, so an injected fault and its
        downstream symptoms line up on one timeline."""
        p = self.probs.get(site, 0.0)
        with self._lock:
            fired = bool(p > 0.0 and self._stream(site).uniform() < p)
            self.log.append((site, fired))
        if fired:
            from ..telemetry import events as _tele
            from ..telemetry import metrics as _tmetrics
            _tele.emit("chaos", severity="warning", site=site,
                       seed=self.seed)
            _tmetrics.counter("mxtpu_chaos_injected_total",
                              "Chaos faults fired", site=site).inc()
        return fired

    def maybe_delay(self, site: str) -> float:
        """Sleep ``delay_s`` when the site's draw fires; returns the delay."""
        if self.should(site) and self.delay_s > 0:
            time.sleep(self.delay_s)
            return self.delay_s
        return 0.0

    def maybe_leak(self, site: str) -> int:
        """When the ``leak`` draw fires at ``site``, allocate
        ``leak_bytes`` of device memory and retain it forever (visible
        to ``jax.live_arrays()``, hence to the ``telemetry.memory``
        ledger). Returns the bytes leaked this call (0 = no fire)."""
        if not self.should("leak"):
            return 0
        n = max(1, self.leak_bytes // 4)
        try:
            import jax.numpy as jnp
            buf = jnp.zeros((n,), "float32")
        except Exception:  # noqa: BLE001 — no jax: leak host memory
            buf = onp.zeros((n,), "float32")
        with self._lock:
            self._leaked.append((site, buf))
        return int(n * 4)

    def scale_ramp(self, site: str) -> float:
        """Advance and return ``site``'s multiplicative chaos ramp
        (``grad_blowup`` / ``activation_drift``): each fired seeded draw
        multiplies the running scale by the site's factor, and the
        CURRENT scale applies from then on — a monotonic, deterministic
        divergence trajectory. Returns 1.0 while the site never fired
        (or its probability is 0)."""
        p = self.probs.get(site, 0.0)
        if p <= 0.0:
            return 1.0
        if self.should(site):
            with self._lock:
                self._ramp[site] = self._ramp.get(site, 0) + 1
        with self._lock:
            k = self._ramp.get(site, 0)
        return float(self._ramp_factor.get(site, 2.0) ** k) if k else 1.0

    def crash(self, site: str, dump: bool = True) -> None:
        """Raise :class:`ChaosCrash` if ``site`` is armed (then disarm).
        A firing crash site is a flight-recorder trigger: the bundle is
        written *before* the raise, exactly what a real SIGKILL handler
        cannot do — except at the recorder's own ``flight.dump`` site,
        which simulates dying mid-dump and must not recurse. A caller
        whose ChaosCrash HANDLER already writes a bundle (the replica
        kill path) passes ``dump=False``: one death, one bundle, and no
        synchronous fsync ahead of the failover that rescues the
        request."""
        if self.armed(site):
            if dump and site != "flight.dump":
                from ..telemetry import flight as _flight
                _flight.dump("chaos_crash", site=site)
            raise ChaosCrash(site)

    def armed(self, site: str) -> bool:
        """Consume one armed count for ``site`` (then disarm) — the
        non-raising twin of :meth:`crash` for faults that corrupt rather
        than kill (the caller applies the fault itself, e.g. the artifact
        cache flipping a byte on disk)."""
        with self._lock:
            left = self._armed.get(site, 0)
            if left <= 0:
                return False
            self._armed[site] = left - 1
        from ..telemetry import events as _tele
        from ..telemetry import metrics as _tmetrics
        _tele.emit("chaos", severity="error", site=site, crash=True,
                   seed=self.seed)
        _tmetrics.counter("mxtpu_chaos_injected_total",
                          "Chaos faults fired", site=site).inc()
        return True

    def note_step(self, step: int) -> None:
        """The trainer's per-step chaos hook for the elastic-drill
        knobs: record the current step (``host_stall`` keys off it) and,
        at the ``host_kill`` step, SIGKILL this process — no Python
        cleanup, no flushed buffers, the exact corpse a preempted host
        leaves. The kill is announced on stderr first (the drill driver
        reads it; a SIGKILLed process can say nothing after)."""
        with self._lock:
            self._last_step = int(step)
        if self.host_kill_step >= 0 and int(step) == self.host_kill_step:
            import signal
            import sys
            print(f"[chaos] host_kill firing at step {step}: "
                  f"SIGKILL pid {os.getpid()}", file=sys.stderr,
                  flush=True)
            from ..telemetry import events as _tele
            _tele.emit("chaos", severity="error", site="host_kill",
                       step=int(step), seed=self.seed)
            os.kill(os.getpid(), signal.SIGKILL)

    def heartbeat_stalled(self) -> bool:
        """Is the ``host_stall`` knob holding heartbeats back? True once
        the trainer has noted a step >= the stall step — the process
        keeps running (and keeps issuing collectives) but its lease goes
        stale, which is exactly the failure the lease watchdog exists
        to catch."""
        if self.host_stall_step < 0:
            return False
        with self._lock:
            last = self._last_step
        return last is not None and last >= self.host_stall_step

    def poison(self, arr):
        """Return a NaN-filled array matching ``arr`` (float dtypes only —
        integer batches poison the first float downstream instead)."""
        a = onp.asarray(arr)
        if a.dtype.kind != "f":
            return arr
        return onp.full_like(a, onp.nan)


_ACTIVE: Optional[ChaosMonkey] = None
_ENV_CHECKED = False


def enable(seed: int = 0, **knobs) -> ChaosMonkey:
    """Install a global :class:`ChaosMonkey`; returns it for inspection."""
    global _ACTIVE
    _ACTIVE = ChaosMonkey(seed=seed, **knobs)
    return _ACTIVE


def disable() -> None:
    global _ACTIVE
    _ACTIVE = None


def enable_from_env() -> Optional[ChaosMonkey]:
    """Parse ``MXTPU_CHAOS`` (``"seed=7,nan_prob=0.01,crash=nd.save"``,
    comma-separated ``k=v``; ``crash`` may repeat) and enable. No-op when
    the variable is unset."""
    spec = os.environ.get("MXTPU_CHAOS")
    if not spec:
        return None
    kw: Dict = {}
    sites = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise MXNetError(f"MXTPU_CHAOS: cannot parse {part!r}")
        k, v = part.split("=", 1)
        k = k.strip()
        if k == "crash":
            sites.append(v.strip())
        elif k in ("seed", "crash_count", "host_kill", "host_stall"):
            kw[k] = int(v)
        else:
            kw[k] = float(v)
    if sites:
        kw["crash_sites"] = sites
    return enable(**kw)


def active() -> Optional[ChaosMonkey]:
    """The installed monkey, or None. Checks ``MXTPU_CHAOS`` once."""
    global _ENV_CHECKED
    if _ACTIVE is None and not _ENV_CHECKED:
        _ENV_CHECKED = True
        enable_from_env()
    return _ACTIVE


class chaos:
    """Scoped enable: ``with fault.inject.chaos(seed=7, nan_prob=1.0): ...``
    (restores whatever was active before — including nothing)."""

    def __init__(self, seed: int = 0, **knobs):
        self._kw = dict(seed=seed, **knobs)
        self._prev = None
        self.monkey: Optional[ChaosMonkey] = None

    def __enter__(self) -> ChaosMonkey:
        global _ACTIVE
        self._prev = _ACTIVE
        self.monkey = _ACTIVE = ChaosMonkey(**self._kw)
        return self.monkey

    def __exit__(self, *exc):
        global _ACTIVE
        _ACTIVE = self._prev


# -- zero-cost hook surface (call sites use these; all no-ops when off) ----

def should(site: str) -> bool:
    m = active()
    return m.should(site) if m is not None else False


def maybe_delay(site: str) -> float:
    m = active()
    return m.maybe_delay(site) if m is not None else 0.0


def maybe_leak(site: str) -> int:
    m = active()
    return m.maybe_leak(site) if m is not None else 0


def scale_ramp(site: str) -> float:
    m = active()
    return m.scale_ramp(site) if m is not None else 1.0


def crash(site: str, dump: bool = True) -> None:
    m = active()
    if m is not None:
        m.crash(site, dump=dump)


def armed(site: str) -> bool:
    m = active()
    return m.armed(site) if m is not None else False


def poison(arr):
    m = active()
    return m.poison(arr) if m is not None else arr


def note_step(step: int) -> None:
    m = active()
    if m is not None:
        m.note_step(step)


def heartbeat_stalled() -> bool:
    m = active()
    return m.heartbeat_stalled() if m is not None else False

"""Atomic, versioned, resumable checkpoint directories.

Reference counterpart: the reference checkpointed with bare
``NDArray::Save`` to a single file (``model.py save_checkpoint``) — a crash
mid-write truncates the file and loses the run. Here a checkpoint is a
*directory per step* finalized by one atomic ``os.replace`` rename, with a
JSON manifest carrying per-array CRC32 checksums, so the invariant is
binary: a checkpoint directory either exists complete and verified, or it
does not exist at all. Layout::

    <root>/
      step-0000000010/
        manifest.json        # format, step, meta, per-array shape/dtype/crc
        arrays.params        # one dmlc .params container (upstream format)
      step-0000000020/
      .tmp-step-0000000030-<pid>/     # in-flight save (ignored by readers)

Write path: arrays + manifest land in the same-filesystem temp dir, the
temp dir is fsync'd, then renamed into place; retention prunes to the
newest ``keep`` completed steps plus any stale temps. Read path:
:func:`load_checkpoint` verifies the manifest checksums before returning
and :func:`load_latest` walks backwards past corrupt/incomplete steps to
the newest checkpoint that verifies — the resume contract a killed run
needs.

The value layer is intentionally dumb: ``{name: numpy array}`` plus a JSON
``meta`` dict. Trainer integration (pytree gather/reshard, RNG keys,
optimizer state naming) lives with the trainers
(:meth:`parallel.ShardedTrainer.save_checkpoint`,
:meth:`gluon.Trainer.save_checkpoint`).
"""
from __future__ import annotations

import json
import os
import shutil
import time
import warnings
import zlib
from typing import Dict, List, Optional, Tuple

import numpy as onp

from ..base import MXNetError
from . import inject

__all__ = ["save_checkpoint", "load_checkpoint", "load_latest",
           "list_checkpoints", "CheckpointError", "CheckpointCorruptError",
           "FORMAT_VERSION", "ARRAYS_FILE", "MANIFEST_FILE"]

FORMAT_VERSION = 1
ARRAYS_FILE = "arrays.params"
MANIFEST_FILE = "manifest.json"
_STEP_PREFIX = "step-"
_TMP_PREFIX = ".tmp-"
_OLD_SUFFIX = ".replaced"


def _recover(root: str) -> None:
    """Heal a same-step replace that crashed between its two renames: the
    displaced-but-complete old copy sits at ``step-N.replaced`` with no
    ``step-N`` — rename it back so the checkpoint is visible again."""
    if not os.path.isdir(root):
        return
    for name in os.listdir(root):
        if not name.endswith(_OLD_SUFFIX):
            continue
        final = os.path.join(root, name[:-len(_OLD_SUFFIX)])
        old = os.path.join(root, name)
        if _parse_step(name[:-len(_OLD_SUFFIX)]) is None:
            continue
        try:
            if not os.path.isdir(final) \
                    and os.path.isfile(os.path.join(old, MANIFEST_FILE)):
                # reader-side self-heal: idempotent (rename either already
                # happened or is a no-op retry), so every host may run it
                os.replace(old, final)  # mxlint: disable=MX902
            else:
                shutil.rmtree(old, ignore_errors=True)
        except OSError:
            pass  # best-effort; the next reader retries


class CheckpointError(MXNetError):
    """No usable checkpoint (missing directory / no completed steps)."""


class CheckpointCorruptError(CheckpointError):
    """A checkpoint directory exists but fails verification (bad manifest,
    checksum mismatch, truncated arrays file)."""


def _step_dirname(step: int) -> str:
    if step < 0:
        raise CheckpointError(f"checkpoint step must be >= 0, got {step}")
    return f"{_STEP_PREFIX}{step:010d}"


def _parse_step(name: str) -> Optional[int]:
    if not name.startswith(_STEP_PREFIX):
        return None
    try:
        return int(name[len(_STEP_PREFIX):])
    except ValueError:
        return None


def _crc(a: onp.ndarray) -> int:
    return zlib.crc32(onp.ascontiguousarray(a).tobytes()) & 0xFFFFFFFF


def _fsync_dir(path: str) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return  # platform without O_RDONLY dirs: rename is still atomic
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def list_checkpoints(root: str) -> List[int]:
    """Completed checkpoint steps under ``root``, ascending. A step counts
    only if its manifest file exists (the last thing a save writes before
    the rename — temp dirs never appear here)."""
    if not os.path.isdir(root):
        return []
    _recover(root)
    steps = []
    for name in os.listdir(root):
        step = _parse_step(name)
        if step is None:
            continue
        if os.path.isfile(os.path.join(root, name, MANIFEST_FILE)):
            steps.append(step)
    return sorted(steps)


def _shard_file(idx: int) -> str:
    return f"arrays-p{idx}.params"


def _marker_file(idx: int) -> str:
    return f"commit-p{idx}.json"


def _commit_timeout_s() -> float:
    try:
        return max(0.1, float(os.environ.get(
            "MXTPU_ELASTIC_COMMIT_TIMEOUT_S", "60")))
    except ValueError:
        return 60.0


def _write_entries(arrays: Dict[str, onp.ndarray]
                   ) -> Tuple[Dict[str, onp.ndarray], Dict[str, dict]]:
    host: Dict[str, onp.ndarray] = {}
    entries: Dict[str, dict] = {}
    for name, a in arrays.items():
        a = onp.asarray(a)
        host[name] = a
        entries[name] = {"shape": list(a.shape), "dtype": a.dtype.name,
                         "crc32": _crc(a)}
    return host, entries


def _write_json(path: str, doc: dict) -> None:
    # callers own the election: paths are either per-host by name (the
    # commit markers) or primary-gated (the manifest) — see
    # _save_multihost, statically unprovable from here
    with open(path, "w") as f:  # mxlint: disable=MX902
        json.dump(doc, f, indent=1, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())


def _finalize_rename(root: str, tmp: str, final: str) -> None:
    inject.crash("checkpoint.finalize")  # died before the atomic rename
    if os.path.isdir(final):
        # same-step replace: os.replace cannot clobber a non-empty dir,
        # so the old copy moves aside first. A crash between the two
        # renames leaves only the aside dir — named so _recover() can
        # rename it back (readers self-heal; the good copy is never in
        # a prunable temp name).
        old = final + _OLD_SUFFIX
        shutil.rmtree(old, ignore_errors=True)   # stale from a crash
        # only the elected primary reaches this helper in a multi-host
        # save (_save_multihost returns early on idx != 0); the single-
        # host path is one writer by construction
        os.replace(final, old)                   # mxlint: disable=MX902
        os.replace(tmp, final)                   # mxlint: disable=MX902
        shutil.rmtree(old, ignore_errors=True)
    else:
        os.replace(tmp, final)                   # mxlint: disable=MX902
    _fsync_dir(root)


def _gather_markers(tmp: str, count: int, timeout_s: float,
                    step: int) -> Dict[int, dict]:
    """Primary-side commit barrier: poll the shared staging dir until
    every host's commit marker exists (each marker is the last thing a
    host fsyncs after its shard). Filesystem polling, not a collective —
    a host that dies mid-shard turns into a loud, attributable timeout
    naming the missing index, never a hang."""
    deadline = time.monotonic() + timeout_s
    missing = list(range(count))
    while True:
        missing = [p for p in range(count)
                   if not os.path.isfile(os.path.join(tmp,
                                                      _marker_file(p)))]
        if not missing:
            break
        if time.monotonic() >= deadline:
            from ..telemetry import flight as _flight
            _flight.dump("checkpoint_commit_timeout",
                         site="checkpoint.manifest", step=step,
                         missing=missing, timeout_s=timeout_s)
            raise CheckpointError(
                f"multi-host checkpoint commit for step {step} timed "
                f"out after {timeout_s:g}s: process(es) {missing} never "
                "wrote their shard commit marker (died mid-shard or "
                "never reached the save) — the torn save stays in its "
                "staging dir, invisible to load_latest")
        time.sleep(0.05)
    markers: Dict[int, dict] = {}
    for p in range(count):
        with open(os.path.join(tmp, _marker_file(p))) as f:
            markers[p] = json.load(f)
    return markers


def _merge_marker_entries(markers: Dict[int, dict],
                          tmp: str, step: int) -> Dict[str, dict]:
    """Merge per-host shard tables into the manifest's array table.
    Overlapping names (replicated params every host gathered) must agree
    bit-for-bit across hosts — a CRC disagreement is SPMD divergence,
    and committing either copy would silently canonize one host's drift:
    refuse loudly instead."""
    merged: Dict[str, dict] = {}
    for p in sorted(markers):
        for name, ent in markers[p].get("arrays", {}).items():
            if name in merged:
                if merged[name]["crc32"] != ent["crc32"]:
                    from ..telemetry import flight as _flight
                    _flight.dump("checkpoint_shard_divergence",
                                 site="checkpoint.manifest", step=step,
                                 array=name, processes=sorted(markers))
                    raise CheckpointError(
                        f"multi-host checkpoint for step {step}: hosts "
                        f"banked DIFFERENT bytes for array {name!r} "
                        f"(crc {merged[name]['crc32']} vs process {p}'s "
                        f"{ent['crc32']}) — SPMD state divergence; "
                        "refusing to commit a manifest that canonizes "
                        "either copy")
                continue
            merged[name] = dict(ent, file=_shard_file(p))
    return merged


def save_checkpoint(root: str, arrays: Dict[str, onp.ndarray],
                    meta: Optional[dict] = None, *, step: int,
                    keep: Optional[int] = 3,
                    process_index: Optional[int] = None,
                    process_count: Optional[int] = None,
                    commit_timeout_s: Optional[float] = None) -> str:
    """Write one atomic checkpoint for ``step``; returns its directory.

    ``arrays`` maps names to host arrays (callers gather device/sharded
    values first); ``meta`` must be JSON-serializable. ``keep`` prunes to
    the newest K completed checkpoints after a successful save (None keeps
    everything). Re-saving an existing step atomically replaces it.

    Multi-host commit protocol (``process_count > 1`` — resolved from
    the live coordination state, or passed explicitly by drills that
    simulate a pod in one process): every host writes its own shard file
    (``arrays-p<idx>.params``) plus a fsync'd commit marker into ONE
    shared staging directory; the elected primary waits for all markers,
    verifies overlapping arrays agree bit-for-bit across hosts, and
    writes the manifest **last**, before the single atomic rename. A
    host killed between its shard write and the primary's manifest
    write leaves a manifest-less staging dir — invisible to
    :func:`load_latest`, so a torn multi-host save can never shadow the
    previous complete step. The marker wait is bounded
    (``MXTPU_ELASTIC_COMMIT_TIMEOUT_S``) and a timeout names the missing
    process index instead of hanging.

    Every successful save records one ``checkpoint.save`` profiler span,
    a ``checkpoint.save`` telemetry event, and (when the goodput ledger
    is on) a ``checkpoint`` attribution note — checkpointing is wall
    time the training loop pays, so it must show up in the run's
    goodput vector, not vanish into ``unattributed``.
    """
    import time as _time
    t_save0 = _time.perf_counter()
    meta = dict(meta or {})
    from ..parallel.dist import world
    widx, wcount = world()
    idx = widx if process_index is None else int(process_index)
    count = wcount if process_count is None else int(process_count)
    final = os.path.join(root, _step_dirname(step))
    if count > 1:
        path = _save_multihost(root, arrays, meta, step=step,
                               idx=idx, count=count,
                               timeout_s=(_commit_timeout_s()
                                          if commit_timeout_s is None
                                          else commit_timeout_s))
        if idx != 0:
            return path
    else:
        # SPMD election (the MX902 invariant): a lone process that still
        # carries a non-zero rank (pre-rendezvous launcher env) must not
        # race the writer it cannot coordinate with — the program does
        # not diverge, only the filesystem effect does.
        from ..parallel.dist import is_primary
        if not is_primary():
            return final
        os.makedirs(root, exist_ok=True)
        tmp = os.path.join(
            root, f"{_TMP_PREFIX}{_step_dirname(step)}-{os.getpid()}")
        if os.path.isdir(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp)
        try:
            host, entries = _write_entries(arrays)
            from ..ndarray.serialization import dmlc_save
            dmlc_save(os.path.join(tmp, ARRAYS_FILE),
                      list(host.values()), list(host.keys()))
            inject.crash("checkpoint.arrays")  # died: arrays, no manifest
            manifest = {"format": FORMAT_VERSION, "step": int(step),
                        "meta": meta, "arrays": entries}
            _write_json(os.path.join(tmp, MANIFEST_FILE), manifest)
            _fsync_dir(tmp)
            _finalize_rename(root, tmp, final)
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
    if keep is not None:
        _prune(root, keep)
    save_ms = (_time.perf_counter() - t_save0) * 1e3
    from .. import profiler as _prof
    from ..telemetry import events as _tele
    from ..telemetry import goodput as _goodput
    _prof.record_span("checkpoint.save", save_ms, t0=t_save0)
    _tele.emit("checkpoint.save", step=step, wall_ms=round(save_ms, 3),
               path=final, arrays=len(arrays), process_index=idx,
               process_count=count)
    if _goodput.enabled():
        _goodput.note("checkpoint", save_ms)
    return final


def _save_multihost(root: str, arrays: Dict[str, onp.ndarray],
                    meta: dict, *, step: int, idx: int, count: int,
                    timeout_s: float) -> str:
    """The shard half of the commit protocol (every host) plus the
    manifest half (primary only). See :func:`save_checkpoint`."""
    final = os.path.join(root, _step_dirname(step))
    # ONE deterministic staging dir all hosts share (same filesystem as
    # the final name — the rename must stay atomic); no pid suffix, the
    # step dirname IS the coordination key
    tmp = os.path.join(root, f"{_TMP_PREFIX}{_step_dirname(step)}-shared")
    os.makedirs(root, exist_ok=True)
    # every host writes ITS shard + marker; per-host file names make the
    # concurrent writes race-free by construction
    # mxlint rationale: per-host shard files are the protocol — the
    # election applies to the manifest + rename below, not the shards
    os.makedirs(tmp, exist_ok=True)
    host, entries = _write_entries(arrays)
    from ..ndarray.serialization import dmlc_save
    try:
        dmlc_save(os.path.join(tmp, _shard_file(idx)),
                  list(host.values()), list(host.keys()))
        inject.crash("checkpoint.arrays")   # died after shard, no marker
        marker = {"format": FORMAT_VERSION, "step": int(step),
                  "process": {"index": idx, "count": count},
                  "arrays": entries}
        _write_json(os.path.join(tmp, _marker_file(idx)), marker)
        _fsync_dir(tmp)
    except BaseException:
        # a failed host removes only ITS files — peers' shards in the
        # shared staging dir are still the primary's to judge (their
        # absence vs the marker wait is what makes the tear loud)
        for f in (_shard_file(idx), _marker_file(idx)):
            try:
                os.unlink(os.path.join(tmp, f))
            except OSError:
                pass
        raise
    if idx != 0:
        return final
    # the elected primary: wait for every host's marker, verify the
    # shard tables agree, and only THEN write the manifest — the last
    # file before the one atomic rename, so load_latest can never see
    # a torn multi-host save
    try:
        markers = _gather_markers(tmp, count, timeout_s, step)
        merged = _merge_marker_entries(markers, tmp, step)
        inject.crash("checkpoint.manifest")  # died between shards+manifest
        manifest = {"format": FORMAT_VERSION, "step": int(step),
                    "meta": meta, "arrays": merged,
                    "shards": {str(p): {"file": _shard_file(p),
                                        "arrays": sorted(
                                            markers[p]["arrays"])}
                               for p in sorted(markers)}}
        _write_json(os.path.join(tmp, MANIFEST_FILE), manifest)
        _fsync_dir(tmp)
        _finalize_rename(root, tmp, final)
    except BaseException:
        # the primary's failure leaves the manifest-less staging dir in
        # place (peers' shards included): invisible to readers, pruned
        # by the next successful save — the same contract as a SIGKILL
        raise
    return final


def _prune(root: str, keep: int) -> None:
    steps = list_checkpoints(root)
    for step in steps[:-keep] if keep > 0 else steps:
        shutil.rmtree(os.path.join(root, _step_dirname(step)),
                      ignore_errors=True)
    for name in os.listdir(root):
        if name.startswith(_TMP_PREFIX):
            # stale in-flight dirs from crashed saves — never loadable
            shutil.rmtree(os.path.join(root, name), ignore_errors=True)


def load_checkpoint(root: str, step: int,
                    verify: bool = True) -> Tuple[Dict[str, onp.ndarray], dict, int]:
    """Load one step → ``(arrays, meta, step)``; checksum-verifies unless
    ``verify=False``. Raises :class:`CheckpointCorruptError` on any
    mismatch between manifest and arrays."""
    _recover(root)
    path = os.path.join(root, _step_dirname(step))
    mpath = os.path.join(path, MANIFEST_FILE)
    if not os.path.isfile(mpath):
        raise CheckpointError(f"no completed checkpoint for step {step} "
                              f"under {root!r}")
    try:
        with open(mpath) as f:
            manifest = json.load(f)
    except (OSError, ValueError) as e:
        raise CheckpointCorruptError(
            f"{mpath}: unreadable manifest: {e}") from e
    if manifest.get("format") != FORMAT_VERSION:
        raise CheckpointCorruptError(
            f"{mpath}: unsupported checkpoint format "
            f"{manifest.get('format')!r} (this build reads "
            f"{FORMAT_VERSION})")
    from ..ndarray.serialization import dmlc_load
    declared = manifest.get("arrays", {})
    # Group declared names by the container that holds them: single-host
    # manifests carry no per-entry "file" (everything lives in
    # ARRAYS_FILE); multi-host manifests record, per array, the shard of
    # the host that banked it. A shard may hold MORE names than the
    # manifest assigns it (replicated params every host gathered — the
    # merge assigned each to its lowest-index writer); only the assigned
    # names are read from each shard.
    by_file: Dict[str, List[str]] = {}
    for name, ent in declared.items():
        by_file.setdefault(ent.get("file", ARRAYS_FILE), []).append(name)
    if not by_file:
        by_file[ARRAYS_FILE] = []
    arrays: Dict[str, onp.ndarray] = {}
    for fname in sorted(by_file):
        apath = os.path.join(path, fname)
        try:
            values, names = dmlc_load(apath)
        except MXNetError as e:
            raise CheckpointCorruptError(f"{apath}: {e}") from e
        held = dict(zip(names, values))
        missing = [n for n in by_file[fname] if n not in held]
        if missing:
            raise CheckpointCorruptError(
                f"{path}: container {fname} is missing declared "
                f"array(s) {sorted(missing)}")
        if fname == ARRAYS_FILE and "shards" not in manifest:
            # single-host container: strict set equality, exactly the
            # pre-protocol contract
            arrays.update(held)
        else:
            for n in by_file[fname]:
                arrays[n] = held[n]
    if set(arrays) != set(declared):
        raise CheckpointCorruptError(
            f"{path}: manifest declares {sorted(declared)} but arrays file "
            f"holds {sorted(arrays)}")
    for name, ent in declared.items():
        a = arrays[name]
        # the dmlc container promotes 0-d arrays to shape (1,) on the wire
        # (upstream has no 0-d records); the manifest keeps the original
        # shape, so restore it — same bytes, same checksum
        if list(a.shape) != ent["shape"]:
            if a.size == int(onp.prod(ent["shape"], dtype=onp.int64)):
                a = arrays[name] = a.reshape(ent["shape"])
            else:
                raise CheckpointCorruptError(
                    f"{path}: array {name!r} is {a.dtype.name}{a.shape}, "
                    f"manifest says {ent['dtype']}{tuple(ent['shape'])}")
        if verify:
            if a.dtype.name != ent["dtype"]:
                raise CheckpointCorruptError(
                    f"{path}: array {name!r} is {a.dtype.name}{a.shape}, "
                    f"manifest says {ent['dtype']}{tuple(ent['shape'])}")
            if _crc(a) != ent["crc32"]:
                raise CheckpointCorruptError(
                    f"{path}: checksum mismatch for array {name!r}")
    return arrays, manifest.get("meta", {}), int(manifest["step"])


def load_latest(root: str, verify: bool = True
                ) -> Tuple[Dict[str, onp.ndarray], dict, int]:
    """Load the newest checkpoint that verifies, walking backwards past
    corrupt steps (each skip warns). Raises :class:`CheckpointError` when
    nothing under ``root`` is loadable — the caller decides whether a cold
    start is acceptable."""
    steps = list_checkpoints(root)
    if not steps:
        raise CheckpointError(f"no completed checkpoints under {root!r}")
    last_err: Optional[Exception] = None
    for step in reversed(steps):
        try:
            return load_checkpoint(root, step, verify=verify)
        except CheckpointCorruptError as e:
            warnings.warn(f"skipping corrupt checkpoint step {step}: {e}")
            last_err = e
    raise CheckpointError(
        f"all {len(steps)} checkpoints under {root!r} failed verification; "
        f"last error: {last_err}")

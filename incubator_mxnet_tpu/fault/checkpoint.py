"""Atomic, versioned, resumable checkpoint directories.

Reference counterpart: the reference checkpointed with bare
``NDArray::Save`` to a single file (``model.py save_checkpoint``) — a crash
mid-write truncates the file and loses the run. Here a checkpoint is a
*directory per step* finalized by one atomic ``os.replace`` rename, with a
JSON manifest carrying per-array CRC32 checksums, so the invariant is
binary: a checkpoint directory either exists complete and verified, or it
does not exist at all. Layout::

    <root>/
      step-0000000010/
        manifest.json        # format, step, meta, per-array shape/dtype/crc
        arrays.params        # one dmlc .params container (upstream format)
      step-0000000020/
      .tmp-step-0000000030-<pid>/     # in-flight save (ignored by readers)

Write path: arrays + manifest land in the same-filesystem temp dir, the
temp dir is fsync'd, then renamed into place; retention prunes to the
newest ``keep`` completed steps plus any stale temps. Read path:
:func:`load_checkpoint` verifies the manifest checksums before returning
and :func:`load_latest` walks backwards past corrupt/incomplete steps to
the newest checkpoint that verifies — the resume contract a killed run
needs.

The value layer is intentionally dumb: ``{name: numpy array}`` plus a JSON
``meta`` dict. Trainer integration (pytree gather/reshard, RNG keys,
optimizer state naming) lives with the trainers
(:meth:`parallel.ShardedTrainer.save_checkpoint`,
:meth:`gluon.Trainer.save_checkpoint`).
"""
from __future__ import annotations

import json
import os
import shutil
import warnings
import zlib
from typing import Dict, List, Optional, Tuple

import numpy as onp

from ..base import MXNetError
from . import inject

__all__ = ["save_checkpoint", "load_checkpoint", "load_latest",
           "list_checkpoints", "CheckpointError", "CheckpointCorruptError",
           "FORMAT_VERSION", "ARRAYS_FILE", "MANIFEST_FILE"]

FORMAT_VERSION = 1
ARRAYS_FILE = "arrays.params"
MANIFEST_FILE = "manifest.json"
_STEP_PREFIX = "step-"
_TMP_PREFIX = ".tmp-"
_OLD_SUFFIX = ".replaced"


def _recover(root: str) -> None:
    """Heal a same-step replace that crashed between its two renames: the
    displaced-but-complete old copy sits at ``step-N.replaced`` with no
    ``step-N`` — rename it back so the checkpoint is visible again."""
    if not os.path.isdir(root):
        return
    for name in os.listdir(root):
        if not name.endswith(_OLD_SUFFIX):
            continue
        final = os.path.join(root, name[:-len(_OLD_SUFFIX)])
        old = os.path.join(root, name)
        if _parse_step(name[:-len(_OLD_SUFFIX)]) is None:
            continue
        try:
            if not os.path.isdir(final) \
                    and os.path.isfile(os.path.join(old, MANIFEST_FILE)):
                # reader-side self-heal: idempotent (rename either already
                # happened or is a no-op retry), so every host may run it
                os.replace(old, final)  # mxlint: disable=MX902
            else:
                shutil.rmtree(old, ignore_errors=True)
        except OSError:
            pass  # best-effort; the next reader retries


class CheckpointError(MXNetError):
    """No usable checkpoint (missing directory / no completed steps)."""


class CheckpointCorruptError(CheckpointError):
    """A checkpoint directory exists but fails verification (bad manifest,
    checksum mismatch, truncated arrays file)."""


def _step_dirname(step: int) -> str:
    if step < 0:
        raise CheckpointError(f"checkpoint step must be >= 0, got {step}")
    return f"{_STEP_PREFIX}{step:010d}"


def _parse_step(name: str) -> Optional[int]:
    if not name.startswith(_STEP_PREFIX):
        return None
    try:
        return int(name[len(_STEP_PREFIX):])
    except ValueError:
        return None


def _crc(a: onp.ndarray) -> int:
    return zlib.crc32(onp.ascontiguousarray(a).tobytes()) & 0xFFFFFFFF


def _fsync_dir(path: str) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return  # platform without O_RDONLY dirs: rename is still atomic
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def list_checkpoints(root: str) -> List[int]:
    """Completed checkpoint steps under ``root``, ascending. A step counts
    only if its manifest file exists (the last thing a save writes before
    the rename — temp dirs never appear here)."""
    if not os.path.isdir(root):
        return []
    _recover(root)
    steps = []
    for name in os.listdir(root):
        step = _parse_step(name)
        if step is None:
            continue
        if os.path.isfile(os.path.join(root, name, MANIFEST_FILE)):
            steps.append(step)
    return sorted(steps)


def save_checkpoint(root: str, arrays: Dict[str, onp.ndarray],
                    meta: Optional[dict] = None, *, step: int,
                    keep: Optional[int] = 3) -> str:
    """Write one atomic checkpoint for ``step``; returns its directory.

    ``arrays`` maps names to host arrays (callers gather device/sharded
    values first); ``meta`` must be JSON-serializable. ``keep`` prunes to
    the newest K completed checkpoints after a successful save (None keeps
    everything). Re-saving an existing step atomically replaces it.

    Every successful save records one ``checkpoint.save`` profiler span,
    a ``checkpoint.save`` telemetry event, and (when the goodput ledger
    is on) a ``checkpoint`` attribution note — checkpointing is wall
    time the training loop pays, so it must show up in the run's
    goodput vector, not vanish into ``unattributed``.
    """
    import time as _time
    t_save0 = _time.perf_counter()
    meta = dict(meta or {})
    # SPMD election (the MX902 invariant): every host runs this same save
    # call — the program must not diverge — but only the elected host may
    # touch the shared checkpoint tree. Non-primary processes return the
    # path the primary is writing; single-process runs are always primary,
    # so this is a no-op outside multi-host jobs.
    from ..parallel.dist import is_primary
    if not is_primary():
        return os.path.join(root, _step_dirname(step))
    os.makedirs(root, exist_ok=True)
    final = os.path.join(root, _step_dirname(step))
    tmp = os.path.join(root, f"{_TMP_PREFIX}{_step_dirname(step)}-{os.getpid()}")
    if os.path.isdir(tmp):
        shutil.rmtree(tmp)
    os.makedirs(tmp)
    try:
        host: Dict[str, onp.ndarray] = {}
        entries: Dict[str, dict] = {}
        for name, a in arrays.items():
            a = onp.asarray(a)
            host[name] = a
            entries[name] = {"shape": list(a.shape), "dtype": a.dtype.name,
                             "crc32": _crc(a)}
        from ..ndarray.serialization import dmlc_save
        dmlc_save(os.path.join(tmp, ARRAYS_FILE),
                  list(host.values()), list(host.keys()))
        inject.crash("checkpoint.arrays")   # died after arrays, no manifest
        manifest = {"format": FORMAT_VERSION, "step": int(step),
                    "meta": meta, "arrays": entries}
        mpath = os.path.join(tmp, MANIFEST_FILE)
        with open(mpath, "w") as f:
            json.dump(manifest, f, indent=1, sort_keys=True)
            f.flush()
            os.fsync(f.fileno())
        _fsync_dir(tmp)
        inject.crash("checkpoint.finalize")  # died before the atomic rename
        if os.path.isdir(final):
            # same-step replace: os.replace cannot clobber a non-empty dir,
            # so the old copy moves aside first. A crash between the two
            # renames leaves only the aside dir — named so _recover() can
            # rename it back (readers self-heal; the good copy is never in
            # a prunable temp name).
            old = final + _OLD_SUFFIX
            shutil.rmtree(old, ignore_errors=True)   # stale from a crash
            os.replace(final, old)
            os.replace(tmp, final)
            shutil.rmtree(old, ignore_errors=True)
        else:
            os.replace(tmp, final)
        _fsync_dir(root)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    if keep is not None:
        _prune(root, keep)
    save_ms = (_time.perf_counter() - t_save0) * 1e3
    from .. import profiler as _prof
    from ..telemetry import events as _tele
    from ..telemetry import goodput as _goodput
    _prof.record_span("checkpoint.save", save_ms, t0=t_save0)
    _tele.emit("checkpoint.save", step=step, wall_ms=round(save_ms, 3),
               path=final, arrays=len(arrays))
    if _goodput.enabled():
        _goodput.note("checkpoint", save_ms)
    return final


def _prune(root: str, keep: int) -> None:
    steps = list_checkpoints(root)
    for step in steps[:-keep] if keep > 0 else steps:
        shutil.rmtree(os.path.join(root, _step_dirname(step)),
                      ignore_errors=True)
    for name in os.listdir(root):
        if name.startswith(_TMP_PREFIX):
            # stale in-flight dirs from crashed saves — never loadable
            shutil.rmtree(os.path.join(root, name), ignore_errors=True)


def load_checkpoint(root: str, step: int,
                    verify: bool = True) -> Tuple[Dict[str, onp.ndarray], dict, int]:
    """Load one step → ``(arrays, meta, step)``; checksum-verifies unless
    ``verify=False``. Raises :class:`CheckpointCorruptError` on any
    mismatch between manifest and arrays."""
    _recover(root)
    path = os.path.join(root, _step_dirname(step))
    mpath = os.path.join(path, MANIFEST_FILE)
    if not os.path.isfile(mpath):
        raise CheckpointError(f"no completed checkpoint for step {step} "
                              f"under {root!r}")
    try:
        with open(mpath) as f:
            manifest = json.load(f)
    except (OSError, ValueError) as e:
        raise CheckpointCorruptError(
            f"{mpath}: unreadable manifest: {e}") from e
    if manifest.get("format") != FORMAT_VERSION:
        raise CheckpointCorruptError(
            f"{mpath}: unsupported checkpoint format "
            f"{manifest.get('format')!r} (this build reads "
            f"{FORMAT_VERSION})")
    from ..ndarray.serialization import dmlc_load
    apath = os.path.join(path, ARRAYS_FILE)
    try:
        values, names = dmlc_load(apath)
    except MXNetError as e:
        raise CheckpointCorruptError(f"{apath}: {e}") from e
    arrays = dict(zip(names, values))
    declared = manifest.get("arrays", {})
    if set(arrays) != set(declared):
        raise CheckpointCorruptError(
            f"{path}: manifest declares {sorted(declared)} but arrays file "
            f"holds {sorted(arrays)}")
    for name, ent in declared.items():
        a = arrays[name]
        # the dmlc container promotes 0-d arrays to shape (1,) on the wire
        # (upstream has no 0-d records); the manifest keeps the original
        # shape, so restore it — same bytes, same checksum
        if list(a.shape) != ent["shape"]:
            if a.size == int(onp.prod(ent["shape"], dtype=onp.int64)):
                a = arrays[name] = a.reshape(ent["shape"])
            else:
                raise CheckpointCorruptError(
                    f"{path}: array {name!r} is {a.dtype.name}{a.shape}, "
                    f"manifest says {ent['dtype']}{tuple(ent['shape'])}")
        if verify:
            if a.dtype.name != ent["dtype"]:
                raise CheckpointCorruptError(
                    f"{path}: array {name!r} is {a.dtype.name}{a.shape}, "
                    f"manifest says {ent['dtype']}{tuple(ent['shape'])}")
            if _crc(a) != ent["crc32"]:
                raise CheckpointCorruptError(
                    f"{path}: checksum mismatch for array {name!r}")
    return arrays, manifest.get("meta", {}), int(manifest["step"])


def load_latest(root: str, verify: bool = True
                ) -> Tuple[Dict[str, onp.ndarray], dict, int]:
    """Load the newest checkpoint that verifies, walking backwards past
    corrupt steps (each skip warns). Raises :class:`CheckpointError` when
    nothing under ``root`` is loadable — the caller decides whether a cold
    start is acceptable."""
    steps = list_checkpoints(root)
    if not steps:
        raise CheckpointError(f"no completed checkpoints under {root!r}")
    last_err: Optional[Exception] = None
    for step in reversed(steps):
        try:
            return load_checkpoint(root, step, verify=verify)
        except CheckpointCorruptError as e:
            warnings.warn(f"skipping corrupt checkpoint step {step}: {e}")
            last_err = e
    raise CheckpointError(
        f"all {len(steps)} checkpoints under {root!r} failed verification; "
        f"last error: {last_err}")

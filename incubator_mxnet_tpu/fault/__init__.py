"""``mx.fault`` — the fault-tolerant training runtime.

Reference counterpart: nothing — the reference trusted the hardware, the
network, and the arithmetic, and production MXNet runs died accordingly
(ps-lite worker loss, truncated ``nd.save`` files, NaN steps discovered
hours later). This subsystem makes the framework own the failure modes a
long TPU training run actually hits (PyGraph's thesis applied to
robustness: the *runtime* around the compiled graph is where production
value lives):

=====================  ====================================================
:mod:`~.checkpoint`    atomic, versioned, checksum-verified checkpoint
                       dirs with retention — ``save_checkpoint`` /
                       ``load_latest``; trainers round-trip their full
                       state (params, optimizer, step, LR position, RNG
                       base key) through it
:mod:`~.guards`        jitted finite-checks on loss / global grad norm
                       with ``warn`` / ``skip_and_rollback`` / ``halt``
                       policies (:class:`StepGuard`)
:mod:`~.watchdog`      per-step deadline timer dumping recompile/last-op
                       diagnostics on hangs (:class:`Watchdog`)
:mod:`~.retry`         env-tunable exponential backoff
                       (:class:`RetryPolicy`) behind the reconnecting
                       ``dist_async`` kvstore client
:mod:`~.inject`        seeded chaos harness — deterministic NaN batches,
                       dropped PS connections, slow steps, and named crash
                       points, so every policy above is a unit test
=====================  ====================================================

Typical wiring::

    guard = mx.fault.StepGuard(policy="skip_and_rollback")
    trainer = mx.parallel.ShardedTrainer(net, loss_fn, "adamw", ...,
                                         guard=guard,
                                         watchdog=mx.fault.Watchdog())
    for step, (x, y) in enumerate(batches):
        trainer.step(x, y)
        if step % 100 == 0:
            trainer.save_checkpoint("ckpts/", keep=3)
    # after a crash:
    trainer.restore_checkpoint("ckpts/")     # newest verified step
"""
from __future__ import annotations

from . import checkpoint  # noqa: F401
from . import guards  # noqa: F401
from . import inject  # noqa: F401
from . import retry  # noqa: F401
from . import watchdog as _watchdog_mod  # noqa: F401
from .checkpoint import (  # noqa: F401
    CheckpointCorruptError, CheckpointError, list_checkpoints,
    load_checkpoint, load_latest, save_checkpoint,
)
from .guards import NonFiniteError, StepGuard, all_finite  # noqa: F401
from .retry import RetryExhausted, RetryPolicy, call_with_retry  # noqa: F401
from .watchdog import Watchdog, WatchdogFlag  # noqa: F401

__all__ = ["checkpoint", "guards", "inject", "retry",
           "save_checkpoint", "load_checkpoint", "load_latest",
           "list_checkpoints", "CheckpointError", "CheckpointCorruptError",
           "StepGuard", "NonFiniteError", "all_finite",
           "Watchdog", "WatchdogFlag",
           "RetryPolicy", "RetryExhausted", "call_with_retry"]

"""``mx.npx`` — numpy-extension ops (reference: python/mxnet/numpy_extension).

Operator-style ops that are not in NumPy (nn layers, sharding helpers) made
available in np-array mode, plus the set_np/reset_np switches.
"""
from __future__ import annotations

from . import ndarray as _nd
from .util import is_np_array, is_np_shape, reset_np, set_np  # noqa: F401

__all__ = ["set_np", "reset_np", "is_np_array", "is_np_shape", "softmax",
           "log_softmax", "relu", "sigmoid", "batch_norm", "fully_connected",
           "convolution", "pooling", "one_hot", "pick", "topk", "waitall",
           "seed"]

softmax = _nd.softmax
log_softmax = _nd.log_softmax
relu = _nd.relu
sigmoid = _nd.sigmoid
batch_norm = _nd.BatchNorm
fully_connected = _nd.FullyConnected
convolution = _nd.Convolution
pooling = _nd.Pooling
one_hot = _nd.one_hot
pick = _nd.pick
topk = _nd.topk
waitall = _nd.waitall


def seed(s):
    from . import random as random_mod
    random_mod.seed(int(s))

"""Parameter/array sharding rules.

Reference counterpart: the kvstore's per-key layout decisions — how
``KVStoreLocal`` shards big arrays across devices
(``MXNET_KVSTORE_BIGARRAY_BOUND``) and how ps-lite range-partitions keys over
servers (``src/kvstore/kvstore_dist.h``). TPU-natively the layout is a
compile-time annotation: each parameter name maps (by regex rule table) to a
:class:`~jax.sharding.PartitionSpec` over the named mesh axes, and XLA's SPMD
partitioner inserts the collectives the kvstore used to run by hand.
"""
from __future__ import annotations

import re
from typing import Dict, List, Optional, Sequence, Tuple, Union

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

P = PartitionSpec

__all__ = ["ShardingRules", "named_sharding", "shard_array", "replicate",
           "data_sharding", "P"]


class ShardingRules:
    """Ordered (regex → PartitionSpec) table; first match wins, default
    replicated. The standard megatron-style table for a transformer:

    >>> rules = ShardingRules([
    ...     (r".*qkv.*weight", P("tp", None)),
    ...     (r".*ffn_in.*weight", P("tp", None)),
    ...     (r".*ffn_out.*weight", P(None, "tp")),
    ...     (r".*embed.*weight", P("tp", None)),
    ... ])
    """

    def __init__(self, rules: Sequence[Tuple[str, PartitionSpec]] = ()):
        self._rules: List[Tuple[re.Pattern, PartitionSpec]] = [
            (re.compile(pat), spec) for pat, spec in rules]

    def add(self, pattern: str, spec: PartitionSpec) -> "ShardingRules":
        self._rules.append((re.compile(pattern), spec))
        return self

    def spec_for(self, name: str, shape: Optional[Tuple[int, ...]] = None,
                 mesh: Optional[Mesh] = None) -> PartitionSpec:
        for pat, spec in self._rules:
            if pat.search(name):
                if shape is not None and mesh is not None and not _divisible(
                        shape, spec, mesh):
                    return P()
                return spec
        return P()

    def sharding_for(self, name: str, mesh: Mesh,
                     shape: Optional[Tuple[int, ...]] = None) -> NamedSharding:
        return NamedSharding(mesh, self.spec_for(name, shape, mesh))

    def __repr__(self):
        return f"ShardingRules({[(p.pattern, s) for p, s in self._rules]})"


def _divisible(shape, spec, mesh) -> bool:
    if len(tuple(spec)) > len(shape):
        return False  # rank mismatch: rule written for a higher-rank param
    for dim, axes in zip(shape, tuple(spec)):
        if axes is None:
            continue
        axes = (axes,) if isinstance(axes, str) else axes
        size = 1
        for a in axes:
            size *= mesh.shape.get(a, 1)
        if size and dim % size:
            return False
    return True


def named_sharding(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, P(*spec))


def replicate(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def data_sharding(mesh: Mesh, batch_axis: int = 0, seq_axis: Optional[int] = None,
                  ndim: int = 2) -> NamedSharding:
    """Input-batch sharding: batch dim over ``dp``, sequence dim over ``sp``
    when those mesh axes have size > 1."""
    spec: List = [None] * ndim
    if batch_axis < ndim and mesh.shape.get("dp", 1) > 1:
        spec[batch_axis] = "dp"
    # rank-1 labels etc. simply don't have a sequence dim to shard
    if seq_axis is not None and seq_axis < ndim and mesh.shape.get("sp", 1) > 1:
        spec[seq_axis] = "sp"
    return NamedSharding(mesh, P(*spec))


def shard_array(x, mesh: Mesh, spec: Union[PartitionSpec, Sequence]) -> jax.Array:
    """Place ``x`` (jax array / numpy) with the given PartitionSpec."""
    if not isinstance(spec, PartitionSpec):
        spec = P(*spec)
    return jax.device_put(x, NamedSharding(mesh, spec))

"""SPMD parallelism over the TPU device mesh.

This package is the TPU-native replacement for the reference's entire
distributed stack (SURVEY §2.5, §5.8): ``src/kvstore/comm.h`` (local device
reduce), ``src/kvstore/kvstore_nccl.h`` (NCCL all-reduce), ``3rdparty/ps-lite``
(multi-node parameter server) and ``tools/launch.py`` (process launcher) all
collapse into ONE mechanism — XLA collectives over the ICI/DCN fabric, driven
by ``jax.sharding`` annotations on a named device mesh.

Modules:

- :mod:`mesh` — named-mesh construction (``dp``/``tp``/``pp``/``sp``/``ep``
  axes), the process-wide default mesh.
- :mod:`sharding` — regex→PartitionSpec rule tables mapping parameter names
  to shardings (the counterpart of the reference's per-key kvstore layout).
- :mod:`collectives` — thin wrappers over ``lax.psum``/``all_gather``/…
  usable inside ``shard_map`` (the NCCL verb surface).
- :mod:`dist` — multi-host runtime init (replaces ``tools/launch.py`` +
  ps-lite role env vars with ``jax.distributed.initialize``).
- :mod:`trainer` — :class:`ShardedTrainer`: one jit-compiled SPMD training
  step (forward+backward+optimizer) over the mesh; the fusion of the
  reference's CachedOp forward/backward + kvstore push/pull + optimizer ops.
- :mod:`ring` — ring attention over the ``sp`` axis (sequence/context
  parallelism; capability-parity-plus, SURVEY §5.7).
- :mod:`pipeline` — GPipe-style microbatched schedule over the ``pp`` axis
  (functional: autodiff derives the backward pipeline).
- :mod:`moe` — expert-parallel mixture-of-experts dispatch over ``ep``
  (all_to_all token exchange).
"""
from .mesh import (  # noqa: F401
    MeshConfig, make_mesh, default_mesh, set_default_mesh, local_mesh,
    AXIS_DP, AXIS_TP, AXIS_PP, AXIS_SP, AXIS_EP,
)
from .sharding import (  # noqa: F401
    ShardingRules, named_sharding, shard_array, replicate, data_sharding,
)
from . import collectives  # noqa: F401
from .collectives import (  # noqa: F401
    all_reduce, all_gather, reduce_scatter, broadcast, ppermute, all_to_all,
)
from .dist import (  # noqa: F401
    finalize, initialize, is_primary, process_count, process_index,
    process_namespace, world,
)
from . import elastic  # noqa: F401
from .elastic import HostLossError  # noqa: F401
from .trainer import ShardedTrainer  # noqa: F401
from .ring import ring_attention, ring_attention_sharded  # noqa: F401
from .pipeline import pipeline_apply, pipeline_sharded  # noqa: F401
from .moe import moe_dispatch, MoEFFN  # noqa: F401

"""ShardedTrainer — one compiled SPMD training step over the mesh.

Reference counterpart: the whole inner loop of SURVEY §3.2 fused into one XLA
executable. What the reference runs as four separate engine phases —
``CachedOp::Forward``, ``Imperative::Backward``, kvstore push/pull
(``KVStoreNCCL`` all-reduce), and per-parameter optimizer ops
(``src/operator/optimizer_op.cc``) — is here a single pjit-compiled pure
function ``(params, opt_state, batch) -> (loss, params', opt_state')`` with
*explicit* ``PartitionSpec`` in/out resources: every parameter, optimizer
shard and batch argument carries its :class:`~jax.sharding.NamedSharding`
into ``jax.jit`` (the pjit formulation), so gradient exchange lowers to XLA
all-reduce over the mesh axes and — under ZeRO-1 — the optimizer update
executes cross-replica sharded (reduce-scatter into the ``dp``-partitioned
update, all-gather of the new weights; Xu et al. 2020, arXiv 2004.13336).
Parameter donation gives the in-place-update memory behavior of
``FMutateInputs``.

This compiled step is THE default execution path whenever a mesh is
configured. The reference's per-parameter kvstore push/pull loop survives
only as a *named fallback* for the async parameter-server scenario: setting
``MXTPU_KVSTORE_FALLBACK=1`` routes :meth:`ShardedTrainer.step` through a
host-side per-parameter exchange over a kvstore backend (``dist_async``
keeps its reconnect/exactly-once-resend semantics untouched) — every other
configuration runs ONE compiled call with zero per-parameter host work.

Whole-step capture (default, ``MXTPU_FUSED_STEP=0`` opts out) finishes
the job: the guard's finite verdict and the LR-schedule position are
computed INSIDE that one donated graph (loss/grad-norm/ok come back as
pinned replicated outputs; the rollback decision stays on host), so a
guarded, LR-scheduled step is still exactly one jitted graph + one host
sync per step. Builds consult the on-disk autotune cache
(``MXTPU_AUTOTUNE_DIR`` — winners banked by ``benchmark/autotune.py``
per (model, mesh_shape, chip)) and overlay the winning env knobs for
exactly the first-trace scope.

Usage::

    mesh = parallel.make_mesh(dp=2, tp=4)
    trainer = parallel.ShardedTrainer(net, loss_fn, 'adamw',
                                      {'learning_rate': 1e-4}, mesh=mesh,
                                      rules=bert_sharding_rules())
    loss = trainer.step(data, label)       # compiled after first call
"""
from __future__ import annotations

import os
import time
from contextlib import nullcontext as _nullcontext
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as onp
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..base import MXNetError
from ..context import current_context
from ..ndarray import NDArray
from .. import autograd
from .. import profiler as _prof
from .. import optimizer as opt_mod
from .. import random as random_mod
from ..gluon import _trace
from ..gluon.block import _TRACING
from .mesh import default_mesh
from .sharding import ShardingRules, data_sharding

P = PartitionSpec

__all__ = ["ShardedTrainer"]


class ShardedTrainer:
    """Drives a HybridBlock's training SPMD over a named mesh.

    Unlike :class:`~incubator_mxnet_tpu.gluon.trainer.Trainer` (which mirrors
    the reference's kvstore push/pull step), this owns the parameters as a
    sharded pytree and updates them functionally each step — the TPU-idiomatic
    formulation. ``sync_to_block()`` writes the current values back into the
    gluon Parameters (for save_parameters / evaluation on one chip).
    """

    def __init__(self, block, loss_fn: Callable, optimizer,
                 optimizer_params: Optional[dict] = None,
                 mesh: Optional[Mesh] = None,
                 rules: Optional[ShardingRules] = None,
                 n_labels: int = 1, seq_axis: Optional[int] = None,
                 donate: bool = True, zero1: Optional[bool] = None,
                 kvstore=None, guard=None, watchdog=None,
                 fused: Optional[bool] = None,
                 autotune_key: Optional[str] = None,
                 numerics=None):
        self._block = block
        self._loss_fn = loss_fn
        self._optimizer = opt_mod.create(
            optimizer, **(optimizer_params or {}))
        self._mesh = mesh if mesh is not None else default_mesh()
        self._rules = rules if rules is not None else ShardingRules()
        self._n_labels = n_labels
        self._seq_axis = seq_axis
        self._donate = donate
        #: ZeRO-1 / cross-replica weight-update sharding (Xu et al. 2020,
        #: arxiv 2004.13336): optimizer states (moments + fp32 masters)
        #: additionally partition over the ``dp`` axis, so XLA
        #: reduce-scatters gradients into the sharded update and
        #: all-gathers the new weights — per-chip optimizer memory drops by
        #: the dp degree while the numerics are unchanged. Default (None):
        #: on whenever the mesh has a real ``dp`` axis — the compiled
        #: cross-replica-sharded weight update IS the default path.
        self._zero1 = (self._mesh.shape.get("dp", 1) > 1
                       if zero1 is None else bool(zero1))
        #: named fallback backend for the async-PS scenario: the
        #: per-parameter host push/pull loop, active only under
        #: MXTPU_KVSTORE_FALLBACK=1 (``kvstore`` names/carries the store —
        #: 'dist_async' keeps its retry/exactly-once client semantics).
        self._kvstore_req = kvstore
        self._kv = None              # resolved lazily on first fallback step
        self._grad_fn = None         # compiled fwd+bwd (fallback path)
        self._step_ndims = None      # batch ranks the built step was pinned to
        self._step_n_data = None     # data-arg count of the built step
        #: staged-recompile cutover flag (:meth:`retune`): the ledger
        #: site the NEXT dispatch's compile is banked under — never
        #: ``trainer.step``, so that site's zero-post-warmup contract
        #: survives a director-driven rebuild
        self._retune_site: Optional[str] = None
        self.last_path: Optional[str] = None
        #: whole-step capture (default on, MXTPU_FUSED_STEP=0 opts out):
        #: the guard's finite verdict and the LR-schedule position are
        #: computed INSIDE the one donated pjit step — loss/grad-norm/ok
        #: come back as pinned replicated outputs, so a guarded,
        #: scheduled step runs exactly ONE jitted graph with one host
        #: sync; the unfused path keeps the PR-2-era shape (separate
        #: jitted finite check, per-step host LR eval + transfer)
        self._fused = (os.environ.get("MXTPU_FUSED_STEP", "1") == "1"
                       if fused is None else bool(fused))
        self._lr_fold = False        # schedule folded into the step graph
        #: jitted-executable invocations the last step() made (fused: 1;
        #: unfused + guard: 2 — the bench.py --proxy fused_step record)
        self.last_step_graphs = 0
        #: autotune-cache key (benchmark/autotune.py winners); default =
        #: the block's class name lowercased — drivers pass the family
        #: name ("bert") so the banked winner and the build agree
        self._autotune_key = (autotune_key
                              or type(block).__name__.lower())
        self._tuned = None           # consult result, resolved at build
        self.autotune_entry: Optional[Dict[str, Any]] = None
        #: in-graph numerics telemetry (telemetry.numerics): an explicit
        #: NumericsConfig, or None = resolve MXTPU_NUMERICS at build
        #: time. When enabled the step graph returns per-site stat
        #: vectors (param:/grad:/act: sites) as extra pinned replicated
        #: outputs of the SAME jitted graph — still exactly one
        #: executable per step — which the host syncs (folded into the
        #: guard's existing device read) every cfg.every steps.
        self._numerics_req = numerics
        self._numerics_cfg = None    # resolved at build (env or explicit)
        self._params = None          # sorted List[Parameter]
        self._param_vals = None      # tuple of sharded jax arrays
        self._opt_states = None      # tuple of per-param state tuples
        self._param_shardings = None  # per-param NamedSharding (post-init)
        self._state_shardings = None  # per-param tuple of NamedShardings
        self._step_fn = None
        self._info: Dict[str, Any] = {}
        self._t = 0
        self._t_dev = None           # device-resident step counter
        self._base_key = None        # device-resident RNG base key
        self._lr_val = None          # python lr the cached device lr mirrors
        self._lr_dev = None
        #: mx.fault wiring (all optional): a StepGuard syncs loss/grad-norm
        #: each step and applies its policy (warn / skip_and_rollback /
        #: halt); a Watchdog flags steps that blow the wall-clock deadline.
        self._guard = guard
        self._watchdog = watchdog
        self._snapshot = None        # (t, param copies, opt-state copies)
        self.last_grad_norm: Optional[float] = None
        self.last_loss: Optional[float] = None
        #: batch (shape, dtype) signatures the compiled step has seen —
        #: a NEW signature after the first is a silent re-trace inside
        #: one jit entry, recorded in the telemetry compile ledger
        self._step_sigs: set = set()
        # registry handles resolved once, not per step (registry lock)
        from ..telemetry import metrics as _tmetrics
        self._m_steps = _tmetrics.counter("mxtpu_train_steps_total",
                                          "Training steps attempted")
        self._m_step_ms = _tmetrics.histogram(
            "mxtpu_train_step_ms", "Training step wall time (ms)")
        self._m_gnorm = _tmetrics.gauge(
            "mxtpu_train_grad_norm",
            "Global gradient norm (guarded steps)")
        self._m_rollbacks = _tmetrics.counter(
            "mxtpu_train_rollbacks_total", "Guarded steps rolled back")
        # Work in the mesh's device context: wrapping step outputs/batches in
        # the *default* (cpu) Context would force sync device→host round
        # trips every step (critical over a tunneled TPU).
        from ..context import context_for_device
        self._ctx = context_for_device(self._mesh.devices.flat[0])

    # ------------------------------------------------------------------
    @property
    def mesh(self) -> Mesh:
        return self._mesh

    @property
    def num_update(self) -> int:
        return self._t

    def _init_state(self, data_args: Sequence[NDArray], warm_ctx) -> None:
        """Warm up the block eagerly (finishes deferred init) in the context
        the parameters live on, then shard every parameter and optimizer
        state onto the mesh by rule."""
        blk = self._block
        with autograd.pause(train_mode=True):
            _TRACING.flag = True
            try:
                blk.forward(*data_args)
            finally:
                _TRACING.flag = False
        items = sorted(blk.collect_params().items())
        self._params = [p for _, p in items]
        opt = self._optimizer
        opt.idx2name = {i: name for i, (name, _) in enumerate(items)}
        # Optimizer state arrays share the weight's layout when same-shaped
        # (momentum / adam moments / fp32 master weights); anything else is
        # replicated. Weights are copied before placement: device_put of an
        # already-matching array shares the buffer, and step-time donation
        # would otherwise delete the gluon Parameter's live data.
        vals, states = [], []
        self._param_shardings, self._state_shardings = [], []
        for i, (name, p) in enumerate(items):
            v = p.data(warm_ctx)._data
            sh = self._rules.sharding_for(name, self._mesh, tuple(v.shape))
            vals.append(jax.device_put(jnp.copy(v), sh))
            self._param_shardings.append(sh)
            placed, st_shs = [], []
            for s in opt.create_state_multi_precision(i, p.data(warm_ctx)):
                st_sh = self._state_sharding(name, tuple(v.shape),
                                             tuple(s.shape))
                placed.append(jax.device_put(s, st_sh))
                st_shs.append(st_sh)
            states.append(tuple(placed))
            self._state_shardings.append(tuple(st_shs))
        self._param_vals = tuple(vals)
        self._opt_states = tuple(states)
        # attribute this trainer's resident state on the device-memory
        # ledger (weak provider: a collected trainer drops off silently)
        from ..telemetry import memory as _memory
        self._mem_unregister = _memory.register_site(
            "trainer.step", self._resident_bytes)

    def _resident_bytes(self) -> int:
        """Device bytes this trainer pins between steps (parameters +
        optimizer states) — the ``trainer.step`` site of the
        ``telemetry.memory`` ledger."""
        total = 0
        for leaf in jax.tree_util.tree_leaves(
                (self._param_vals or (), self._opt_states or ())):
            total += int(getattr(leaf, "nbytes", 0) or 0)
        return total

    def _state_sharding(self, name, wshape, sshape) -> NamedSharding:
        """ONE policy for optimizer-state placement (used by init and
        restore): weight-shaped states follow the weight's rule spec — plus
        the zero1 dp-partition when enabled — everything else replicates."""
        spec = (self._rules.spec_for(name, wshape, self._mesh)
                if sshape == wshape else P())
        if self._zero1 and sshape == wshape:
            spec = self._zero1_spec(spec, sshape)
        return NamedSharding(self._mesh, spec)

    def _zero1_spec(self, spec, shape):
        """Extend a weight's PartitionSpec with a ``dp`` factor on the first
        axis that has room — the optimizer-state layout of ZeRO stage 1."""
        dp = self._mesh.shape.get("dp", 1)
        if dp == 1:
            return spec
        entries = list(tuple(spec)) + [None] * (len(shape) - len(tuple(spec)))
        for e in entries:
            used = e if isinstance(e, tuple) else ((e,) if e else ())
            if "dp" in used:
                return P(*entries)      # already dp-partitioned by rule
        for ax in range(len(shape)):
            e = entries[ax]
            used = tuple(e) if isinstance(e, tuple) else ((e,) if e else ())
            cur = 1
            for a in used:
                cur *= self._mesh.shape[a]
            if shape[ax] % (cur * dp) == 0:
                entries[ax] = used + ("dp",)
                return P(*entries)
        return spec                     # nothing divisible: stay replicated

    # ------------------------------------------------------------------
    def _per_param_hparams(self):
        """(lr_mults, wds, mp) — the per-parameter hyperparameter vectors
        shared by the compiled pjit step and the kvstore-fallback update,
        so the two paths can never apply different schedules."""
        opt, params = self._optimizer, self._params
        lr_mults = [opt._get_lr(i) / max(opt.learning_rate, 1e-30)
                    for i in range(len(params))]
        wds = [opt._get_wd(i) for i in range(len(params))]
        # Mixed precision: state[0] is the fp32 master weight (reference:
        # Optimizer.update_multi_precision master branch).
        mp = [bool(opt.multi_precision
                   and self._param_vals[i].dtype in (jnp.float16, jnp.bfloat16)
                   and self._opt_states[i]
                   and self._opt_states[i][0].dtype == jnp.float32
                   and self._opt_states[i][0].shape == self._param_vals[i].shape)
              for i in range(len(params))]
        return lr_mults, wds, mp

    def step_shardings(self, batch_ndims: Sequence[int]):
        """The explicit pjit resource contract of the compiled step:
        ``(in_shardings, out_shardings)`` NamedSharding pytrees matching
        ``step(param_vals, opt_states, key, lr, t, *batch)`` →
        ``(loss, gnorm, new_vals, new_states, effects, t+1[, ok][, stats])``
        (``ok`` — the in-graph guard verdict — only on the fused path;
        ``stats`` — the per-site numerics pytree — only when numerics
        telemetry is enabled for this build). Scalars and the RNG key
        replicate; parameters/optimizer shards carry their rule (+ zero1
        ``dp``) layouts in AND out, so the optimizer update is compiled
        cross-replica sharded and the next call sees identical
        placements (no silent re-trace); batch arguments take the
        batch-over-``dp`` / seq-over-``sp`` data sharding."""
        repl = NamedSharding(self._mesh, P())
        batch_sh = tuple(
            data_sharding(self._mesh, batch_axis=0, seq_axis=self._seq_axis,
                          ndim=nd) for nd in batch_ndims)
        params_sh = tuple(self._param_shardings)
        states_sh = tuple(tuple(s) for s in self._state_shardings)
        in_shardings = (params_sh, states_sh, repl, repl, repl) + batch_sh
        # effects (aux state: batchnorm running stats) replicate — a repl
        # prefix broadcasts over that subtree whatever its arity
        out_shardings = (repl, repl, params_sh, states_sh, repl, repl)
        if self._fused:
            # the guard verdict: a pinned replicated scalar, read back in
            # the SAME host sync as loss/grad-norm
            out_shardings = out_shardings + (repl,)
        if self._numerics_cfg is not None and self._numerics_cfg.enabled:
            # numerics stats: a dict subtree of small replicated vectors
            # — one repl prefix broadcasts over it whatever its arity
            out_shardings = out_shardings + (repl,)
        return in_shardings, out_shardings

    def _make_loss_grads(self, n_data: int) -> Callable:
        """``(param_vals, key, t, *batch) -> (loss, gnorm, grads, effects,
        taps)`` — the fwd+bwd half of the step, shared verbatim by the
        compiled pjit step and the kvstore-fallback path so their
        gradients are the same function of the same inputs. ``taps`` is
        the tuple of in-graph activation stats collected from
        ``numerics.tap()`` sites during the forward trace (site names
        recorded in ``info['tap_sites']``); empty when numerics is off
        — tap stat tracers belong to the inner differentiated trace, so
        like the aux effects they MUST ride out through ``has_aux``."""
        blk, params = self._block, self._params
        loss_fn, ctx, info = self._loss_fn, self._ctx, self._info
        num_cfg = self._numerics_cfg
        num_on = num_cfg is not None and num_cfg.enabled

        def loss_grads(param_vals, key, t, *batch_vals):
            # Per-step randomness is derived ON DEVICE from one resident base
            # key — the host passes the same array every step, so there is no
            # eager key-split or host→device key transfer in the loop (those
            # cost ~7ms/step over a tunneled TPU; profiler-verified).
            key = jax.random.fold_in(key, t)

            def loss_of(pvals):
                from ..telemetry import numerics as _numerics
                proxies = {id(p): NDArray(v, ctx=ctx)
                           for p, v in zip(params, pvals)}
                ins = [NDArray(v, ctx=ctx) for v in batch_vals]
                col_ctx = (_numerics.collecting(num_cfg) if num_on
                           else _nullcontext())
                _TRACING.flag = True
                try:
                    with autograd.pause(train_mode=True), \
                            random_mod.trace_rng(key), \
                            col_ctx as col, \
                            _trace.TraceScope(proxies) as scope:
                        out = blk.forward(*ins[:n_data])
                        loss = loss_fn(out, *ins[n_data:])
                finally:
                    _TRACING.flag = False
                lv = loss._data if isinstance(loss, NDArray) else loss
                info["effects"] = list(scope.effect_keys)
                info["tap_sites"] = list(col.names) if num_on else []
                taps = tuple(col.values) if num_on else ()
                return jnp.mean(lv), (tuple(scope.effect_values), taps)

            (loss, (effects, taps)), grads = jax.value_and_grad(
                loss_of, has_aux=True)(param_vals)
            # Global grad norm, fused into the step (fp32 accumulation so a
            # bf16 overflow can't hide): one scalar out, consumed by the
            # fault.StepGuard finite/limit check and exposed as
            # trainer.last_grad_norm.
            gnorm = jnp.sqrt(sum(
                jnp.sum(jnp.square(g.astype(jnp.float32))) for g in grads))
            return loss, gnorm, grads, effects, taps

        return loss_grads

    def _resolve_numerics(self):
        """Resolve the numerics config ONCE per trainer (explicit ctor
        config wins; else the env) — build-time, like the autotune
        consult, so flipping MXTPU_NUMERICS mid-run cannot silently
        re-trace a compiled step."""
        if self._numerics_cfg is None:
            from ..telemetry import numerics as _numerics
            self._numerics_cfg = (self._numerics_req
                                  if self._numerics_req is not None
                                  else _numerics.config())
        return self._numerics_cfg

    def _build_step(self, n_data: int, batch_ndims: Sequence[int]) -> Callable:
        opt = self._optimizer
        param_shardings = self._param_shardings
        state_shardings = self._state_shardings
        lr_mults, wds, mp = self._per_param_hparams()
        num_cfg = self._resolve_numerics()
        num_on = num_cfg.enabled
        param_names = [name for name, _ in
                       sorted(self._block.collect_params().items())]
        # local alias, NOT self: the jitted step closure must never
        # capture the trainer — that cycle would keep dead trainers
        # (and their weak memory-ledger providers) alive past refcount
        step_info = self._info
        loss_grads = self._make_loss_grads(n_data)
        fused = self._fused
        # LR-schedule position folded into the graph (whole-step capture):
        # with a traceable scheduler the per-step LR is a function of the
        # device-resident update counter — no host schedule eval, no
        # per-step host→device LR transfer. ``lr`` stays an input scalar
        # carrying the base LR (so an explicit set_learning_rate still
        # rescales without a re-trace); the schedule position is t-1,
        # this step's optimizer.num_update.
        sched = getattr(opt, "lr_scheduler", None)
        fold_lr = bool(fused and sched is not None
                       and hasattr(sched, "jax_lr"))
        self._lr_fold = fold_lr
        base_lr = (float(getattr(sched, "base_lr", 0.0) or 0.0)
                   if fold_lr else None)

        def step(param_vals, opt_states, key, lr, t, *batch_vals):
            if fold_lr:
                # scale by lr/baked_base: the lr input tracks the
                # scheduler's live base_lr (_refresh_scalars), so a
                # mid-run base override rescales the folded schedule
                # without a re-trace; at the baked base the factor is 1
                scale = (lr / jnp.float32(base_lr)) if base_lr else 1.0
                lr = sched.jax_lr(t - 1) * scale
            loss, gnorm, grads, effects, taps = loss_grads(
                param_vals, key, t, *batch_vals)
            stats = None
            if num_on:
                # per-site tensor stats, computed IN-GRAPH (a handful of
                # fused reductions) and returned as extra pinned
                # replicated outputs of this same executable — never a
                # host callback (the MX603/MX701 anti-pattern)
                from ..telemetry import numerics as _numerics
                stats = {}
                for name, w, g in zip(param_names, param_vals, grads):
                    s = f"param:{name}"
                    if num_cfg.wants(s):
                        stats[s] = _numerics.graph_stats(w, num_cfg)
                    s = f"grad:{name}"
                    if num_cfg.wants(s):
                        stats[s] = _numerics.graph_stats(g, num_cfg)
                for site, val in zip(step_info.get("tap_sites", ()),
                                     taps):
                    stats[site] = val
            constrain = jax.lax.with_sharding_constraint
            new_vals, new_states = [], []
            for i, (w, g, s) in enumerate(zip(param_vals, grads, opt_states)):
                if mp[i]:
                    nm, ns = opt.step(s[0], g.astype(jnp.float32), tuple(s[1:]),
                                      lr * lr_mults[i], wds[i], t)
                    nv = nm.astype(w.dtype)
                    nst = (nm,) + tuple(ns)
                else:
                    nw, ns = opt.step(w, g.astype(w.dtype), s,
                                      lr * lr_mults[i], wds[i], t)
                    nv = nw.astype(w.dtype)
                    nst = tuple(ns)
                # Pin layouts so step outputs keep the step-input shardings:
                # under zero1 the update math runs dp-sharded (XLA
                # reduce-scatters the grads into it) and ONLY the new weight
                # is gathered back to the rule layout — and the next call
                # sees identical input shardings (no silent recompile).
                nv = constrain(nv, param_shardings[i])
                nst = tuple(constrain(a, sh)
                            for a, sh in zip(nst, state_shardings[i]))
                new_vals.append(nv)
                new_states.append(nst)
            if fused:
                # the guard's finite check, captured in-graph: one fused
                # reduction instead of a separate jitted call — the
                # rollback DECISION stays on host (_apply_guard)
                ok = jnp.logical_and(jnp.isfinite(loss).all(),
                                     jnp.isfinite(gnorm))
                out = (loss, gnorm, tuple(new_vals), tuple(new_states),
                       effects, t + 1, ok)
            else:
                out = (loss, gnorm, tuple(new_vals), tuple(new_states),
                       effects, t + 1)
            if num_on:
                out = out + (stats,)
            return out

        # The explicit pjit contract: named in/out resources + donation.
        # With out_shardings pinned, XLA's SPMD partitioner OWNS the
        # gradient exchange (all-reduce over dp — reduce-scatter +
        # all-gather under zero1) and the donated param/state buffers are
        # updated in place: zero per-parameter host work on the hot path.
        in_shardings, out_shardings = self.step_shardings(batch_ndims)
        donate = (0, 1, 4) if self._donate else ()
        return jax.jit(step, in_shardings=in_shardings,
                       out_shardings=out_shardings, donate_argnums=donate)

    # ------------------------------------------------------------------
    # named fallback: the per-parameter kvstore push/pull loop (async-PS)
    # ------------------------------------------------------------------
    @staticmethod
    def kv_fallback_active() -> bool:
        """True when MXTPU_KVSTORE_FALLBACK=1 routes the step through the
        host-side per-parameter kvstore exchange (the async parameter-
        server scenario). Explicit opt-in: every other configuration runs
        the compiled pjit step. Read straight off the environment — this
        sits on the hot step path, where an import + catalog lookup per
        step is measurable dispatch tax (profiler-gated at >=95%
        instrumented); the catalog entry lives in util.ENV_VARS."""
        return os.environ.get("MXTPU_KVSTORE_FALLBACK", "0") == "1"

    def _resolve_kvstore(self):
        if self._kv is None:
            if self._kvstore_req is None or isinstance(self._kvstore_req, str):
                from .. import kvstore as kv_mod
                self._kv = kv_mod.create(self._kvstore_req or "device")
            else:
                self._kv = self._kvstore_req    # explicit store object
            for i, v in enumerate(self._param_vals):
                self._kv.init(i, NDArray(jax.device_get(v)))
        return self._kv

    def _kv_step(self, vals, n_data: int):
        """One fallback step: compiled fwd+bwd, then a PER-PARAMETER
        Python push/pull loop through the kvstore (host round trip per
        key — exactly the dispatch tax the pjit path removes), then the
        eager optimizer update. The kvstore client's semantics ride along
        untouched: a ``dist_async`` store keeps its reconnect, bounded
        retry and versioned exactly-once resend behavior per key."""
        if self._grad_fn is None:
            self._resolve_numerics()
            self._grad_fn = jax.jit(self._make_loss_grads(n_data))
        kv = self._resolve_kvstore()
        # taps are discarded on this path: numerics decimation/recording
        # belongs to the compiled pjit step (the fallback is the legacy
        # per-parameter host loop — it was never capture-clean)
        loss, gnorm, grads, effects, _taps = self._grad_fn(
            self._param_vals, self._base_key, self._t_dev, *vals)
        lr_mults, wds, mp = self._per_param_hparams()
        opt = self._optimizer
        # the whole update runs host-side: every operand comes off the
        # mesh (the per-parameter device→host sync IS this path's cost).
        # The LR comes straight off the host schedule — the device mirror
        # may hold only the base LR when a pjit build folded the schedule
        t = jnp.asarray(jax.device_get(self._t_dev))
        lr = jnp.asarray(float(self._optimizer.learning_rate), jnp.float32)
        new_vals, new_states = [], []
        for i, (wm, g, sm) in enumerate(zip(self._param_vals, grads,
                                            self._opt_states)):
            # the reference Trainer.step shape: push grad i, pull the
            # merged value back — one host round trip per parameter
            merged = kv.pushpull(i, NDArray(jax.device_get(g)))
            gm = jnp.asarray(merged._data)
            w = jnp.asarray(jax.device_get(wm))
            s = tuple(jnp.asarray(jax.device_get(a)) for a in sm)
            if mp[i]:
                nm, ns = opt.step(s[0], gm.astype(jnp.float32), tuple(s[1:]),
                                  lr * lr_mults[i], wds[i], t)
                nv = nm.astype(w.dtype)
                nst = (nm,) + tuple(ns)
            else:
                nw, ns = opt.step(w, gm.astype(w.dtype), s,
                                  lr * lr_mults[i], wds[i], t)
                nv = nw.astype(w.dtype)
                nst = tuple(ns)
            new_vals.append(jax.device_put(nv, self._param_shardings[i]))
            new_states.append(tuple(
                jax.device_put(a, sh)
                for a, sh in zip(nst, self._state_shardings[i])))
        self._param_vals = tuple(new_vals)
        self._opt_states = tuple(new_states)
        self._t_dev = self._t_dev + 1
        return loss, gnorm, effects

    # ------------------------------------------------------------------
    def _ensure_built(self, n_data: int, ndims: Tuple[int, ...]) -> None:
        """(Re)build the pjit step for these batch ranks, consulting the
        autotune cache ONCE per trainer first (``MXTPU_AUTOTUNE_DIR``):
        a banked winner's env knobs overlay the first trace, so the
        tuned configuration is applied per build, not per shell."""
        if self._step_fn is not None and ndims == self._step_ndims:
            return
        if self._tuned is None:
            from .. import autotune as _autotune
            self._tuned = _autotune.consult(
                "trainer.step", self._autotune_key, mesh=self._mesh) or {}
            self.autotune_entry = self._tuned or None
        self._step_fn = self._build_step(n_data, ndims)
        self._step_ndims = ndims
        self._step_n_data = n_data

    def _refresh_scalars(self, next_t: int) -> None:
        """Materialize the device-resident step scalars. With the LR
        schedule folded into the graph the LR input is the base LR set
        ONCE — no per-step host schedule eval or transfer; otherwise the
        host mirror refreshes whenever the schedule moved."""
        if self._lr_fold:
            # the lr input carries the scheduler's CURRENT base LR; the
            # step graph computes jax_lr(t) * (lr / baked_base), so a
            # live `sched.base_lr = x` rescales the folded schedule on
            # the next step without a re-trace (at the baked base the
            # factor is exactly 1)
            sched = self._optimizer.lr_scheduler
            base = float(getattr(sched, "base_lr", 0.0) or 0.0)
            if self._lr_dev is None or self._lr_val != base:
                self._lr_val = base
                self._lr_dev = jnp.asarray(base, jnp.float32)
        elif self._lr_dev is None \
                or self._lr_val != self._optimizer.learning_rate:
            self._lr_val = self._optimizer.learning_rate
            self._lr_dev = jnp.asarray(self._lr_val, jnp.float32)
        if self._t_dev is None:
            self._t_dev = jnp.asarray(next_t, jnp.int32)
        if self._base_key is None:
            self._base_key = random_mod.next_key(self._ctx)

    def prepare(self, *batch) -> None:
        """Build everything :meth:`step` needs WITHOUT dispatching (no
        XLA compile): eager parameter init, sharding resolution, the
        autotune consult, the pjit step function, and the device-resident
        scalars. After ``prepare()`` the full fwd+bwd+optimizer graph is
        traceable offline (``analysis.hlo`` / ``benchmark.autotune``
        price it through ``jax.make_jaxpr``) before any step has run —
        the autotuner's trace-only scoring path."""
        n_data = len(batch) - self._n_labels
        if n_data < 1:
            raise MXNetError("prepare() needs at least one data argument")
        if self._params is None:
            warm_ctx = current_context()
            warm = [a if isinstance(a, NDArray) else NDArray(a, ctx=warm_ctx)
                    for a in batch[:n_data]]
            self._init_state(warm, warm_ctx)
        vals = self.place(*batch)
        self._ensure_built(n_data, tuple(v.ndim for v in vals))
        self._refresh_scalars(self._t + 1)

    def retune(self, entry: Optional[Dict[str, Any]] = None,
               site: str = "director.recompile") -> None:
        """Stage a recompile cutover (the flight director's
        ``compute_bound`` remediation): swap the tuned config and rebuild
        the pjit step entry NOW — no dispatch, no XLA compile yet (pjit
        traces lazily), so the running step is never interrupted. The
        NEXT :meth:`step` traces the fresh entry under the new config's
        env overlay and pays exactly one compile, which is banked on the
        compile ledger under ``site`` — never ``trainer.step``, so that
        site's ``assert_zero_post_warmup`` contract still holds across
        the cutover. Safe mid-run: parameters, optimizer state, the step
        counter, and the seen-signature set are all untouched.

        ``entry`` is an autotune-cache entry (``{"config": {"env": ...},
        ...}``); ``{}`` clears the tuned overlay, ``None`` keeps the
        current one (rebuild only — still a guaranteed fresh compile)."""
        if self._step_fn is None or self._step_ndims is None:
            raise MXNetError("retune() before the first build — run "
                             "prepare() or step() first")
        if self.kv_fallback_active():
            raise MXNetError("retune() stages a pjit rebuild; the "
                             "kvstore-fallback path has no pjit step")
        if entry is not None:
            self._tuned = dict(entry)
            self.autotune_entry = self._tuned or None
        from .. import autotune as _autotune
        tune_ctx = (_autotune.applied(self._tuned) if self._tuned
                    else _nullcontext())
        with tune_ctx:
            self._step_fn = self._build_step(self._step_n_data,
                                             self._step_ndims)
        self._retune_site = site

    # ------------------------------------------------------------------
    def step_trace_args(self, *batch):
        """Live argument tuple matching the jitted step's signature, for
        offline inspection (``mx.analysis.hlo`` traces the full
        fwd+bwd+optimizer graph without executing it). Requires a built
        step function — one completed :meth:`step`, or a compile-free
        :meth:`prepare`."""
        if self._step_fn is None or self._base_key is None:
            raise MXNetError("step_trace_args() needs a built step "
                             "function: run one step() (or prepare()) "
                             "first")
        vals = self.place(*batch)
        return (self._param_vals, self._opt_states, self._base_key,
                self._lr_dev, self._t_dev) + tuple(vals)

    # ------------------------------------------------------------------
    def place(self, *batch):
        """Place batch arrays onto the mesh with the data sharding (batch
        over ``dp``, sequence over ``sp``). One hop host→mesh; arrays already
        resident with a matching sharding pass through for free — call this
        from the input pipeline to overlap transfer with compute."""
        vals = []
        for a in batch:
            if isinstance(a, NDArray):
                v = a._data
            elif isinstance(a, jax.Array):
                v = a
            else:
                v = onp.asarray(a)
            sh = data_sharding(self._mesh, batch_axis=0,
                               seq_axis=self._seq_axis, ndim=v.ndim)
            vals.append(jax.device_put(v, sh))
        return tuple(vals)

    def step(self, *batch) -> NDArray:
        """Run one training step on a global batch; returns the mean loss.

        ``batch`` = data arguments then ``n_labels`` label arguments, as
        NDArrays or numpy/jax arrays (placed with batch-over-``dp``,
        seq-over-``sp`` sharding).
        """
        n_data = len(batch) - self._n_labels
        if n_data < 1:
            raise MXNetError("step() needs at least one data argument")
        from ..fault import inject as _inject
        from ..telemetry import compile_log as _clog
        from ..telemetry import events as _tele
        t_step0 = time.perf_counter()
        # elastic step-boundary hooks: poll() surfaces any host loss the
        # lease watchdog detected since the last step (one lock-free list
        # read when the pod is healthy — never I/O on the hot path), and
        # note_step drives the seeded host_kill/host_stall chaos knobs
        from . import elastic as _elastic
        _elastic.poll()
        _inject.note_step(self._t + 1)
        if _inject.active() is not None:
            # the poisoned batch belongs to the step about to run — bind
            # its id so the chaos event and the guard verdict correlate
            with _tele.step_scope(self._t + 1):
                batch = self._chaos_batch(batch, n_data)
        if self._params is None:
            # Eager warmup runs wherever the parameters were initialized
            # (current context), NOT on the mesh.
            warm_ctx = current_context()
            warm = [a if isinstance(a, NDArray) else NDArray(a, ctx=warm_ctx)
                    for a in batch[:n_data]]
            self._init_state(warm, warm_ctx)
        t_place0 = time.perf_counter()
        vals = self.place(*batch)
        place_ms = (time.perf_counter() - t_place0) * 1e3
        # Dispatch: a configured mesh runs the ONE compiled pjit step
        # (explicit in/out PartitionSpecs, donated buffers) — the default
        # path. The per-parameter kvstore loop survives only behind the
        # MXTPU_KVSTORE_FALLBACK=1 opt-in (async-PS scenario).
        fallback = self.kv_fallback_active()
        if not fallback:
            # the jit entry's batch in_shardings are rank-pinned; a batch
            # of NEW ranks rebuilds the entry (a fresh compile, noted in
            # the ledger via its new signature — the same cost the
            # re-trace paid before shardings were explicit)
            self._ensure_built(n_data, tuple(v.ndim for v in vals))
        if self._guard is not None:
            self._maybe_snapshot()
        self._t += 1
        attempted = self._t          # event id even if a rollback resets _t
        self._refresh_scalars(self._t)
        # a new batch (shape, dtype) signature re-traces inside the jit
        # entry — the classic silent recompile; the ledger makes it visible
        sig = tuple((tuple(v.shape), str(v.dtype)) for v in vals)
        new_sig = sig not in self._step_sigs
        first_sig = not self._step_sigs
        from .mesh import active_mesh
        from ..telemetry import trace as _trace
        wd = self._watchdog
        # one root span per step: the kvstore-fallback push/pull hops,
        # guard verdicts, chaos draws, and the profiler's step frame all
        # stitch under it — the training twin of the router's
        # per-request tree (head sampling decides per step)
        with _tele.step_scope(attempted), \
                _trace.span("train.step", step=attempted,
                            path="kvstore_fallback" if fallback
                            else "pjit"):
            with wd.watch(step=self._t, block=self._block) if wd is not None \
                    else _nullcontext():
                _inject.maybe_delay("slow_step")
                # chaos leak site: retains device arrays so the memory
                # ledger's leak watchdog is deterministically testable
                _inject.maybe_leak("trainer.step")
                t_disp0 = time.perf_counter()
                self.last_step_graphs = 1       # the step executable
                ok = None
                # a NEW signature is about to trace: overlay the autotune
                # winner's env knobs for exactly that trace (user-set env
                # always wins; see autotune.applied). A staged retune()
                # cutover re-traces a FRESH pjit entry at a seen
                # signature — same overlay rule applies
                retuned_now = self._retune_site is not None and not fallback
                if (new_sig or retuned_now) and not fallback and self._tuned:
                    from .. import autotune as _autotune
                    tune_ctx = _autotune.applied(self._tuned)
                else:
                    tune_ctx = _nullcontext()
                # a RESOURCE_EXHAUSTED out of dispatch (or the guard's
                # device sync below) writes ONE OOM flight bundle with
                # the memory ledger + static peaks, then re-raises
                from ..telemetry import memory as _memory
                with _memory.oom_guard("trainer.step", step=attempted), \
                        active_mesh(self._mesh), tune_ctx:
                    # bound during (first-call) tracing so mesh-aware ops
                    # lower to mesh collectives — e.g. attention → ring
                    # over sp
                    stats_dev = None
                    num_cfg = self._numerics_cfg
                    num_on = (not fallback and num_cfg is not None
                              and num_cfg.enabled)
                    if fallback:
                        loss, gnorm, effects = self._kv_step(vals, n_data)
                    else:
                        out = self._step_fn(self._param_vals,
                                            self._opt_states,
                                            self._base_key, self._lr_dev,
                                            self._t_dev, *vals)
                        if num_on:
                            stats_dev = out[-1]
                            out = out[:-1]
                        if self._fused:
                            (loss, gnorm, self._param_vals,
                             self._opt_states, effects, self._t_dev,
                             ok) = out
                        else:
                            (loss, gnorm, self._param_vals,
                             self._opt_states, effects,
                             self._t_dev) = out
                self.last_path = "kvstore_fallback" if fallback else "pjit"
                dispatch_ms = (time.perf_counter() - t_disp0) * 1e3
                from ..telemetry import collective_ledger as _cledger
                if new_sig:
                    self._step_sigs.add(sig)
                    _clog.note("trainer.step", sig, wall_ms=dispatch_ms,
                               warmup=first_sig)
                    # bank this build's collective-schedule fingerprint
                    # (one re-trace, no XLA compile; ledger off = one env
                    # read) — a post-warmup rebank in a multi-process run
                    # crosschecks immediately: the one-host-recompiled
                    # divergence onset
                    if _cledger.enabled() and not fallback:
                        _cledger.bank_trainer(self, vals)
                if retuned_now:
                    # the staged cutover's one compile: seen signature,
                    # fresh pjit entry — banked under the staging site
                    # (director.recompile), never trainer.step, so the
                    # step site's zero-post-warmup contract survives
                    _clog.note(self._retune_site, sig,
                               wall_ms=dispatch_ms, warmup=None)
                    self._retune_site = None
                # the dispatch ring: what this pod member actually ran,
                # in order — the flight bundle's cross-host diff surface
                _cledger.note_dispatch("trainer.step", sig)
                # numerics decimation: the host SYNCS the stat outputs
                # only every cfg.every steps (first step included), and
                # the read rides the guard's existing single device
                # sync — stats never add a host round trip of their own
                read_stats = (num_on and stats_dev is not None
                              and (attempted - 1) % num_cfg.every == 0)
                t_sync0 = time.perf_counter()
                with _memory.oom_guard("trainer.step", step=attempted):
                    rolled_back = (self._guard is not None
                                   and self._apply_guard(
                                       loss, gnorm, ok,
                                       stats_dev=(stats_dev if read_stats
                                                  else None),
                                       step=attempted))
                    if read_stats and self._guard is None:
                        # unguarded loop: the decimated read is the only
                        # sync this step performs
                        from ..telemetry import numerics as _numerics
                        _numerics.record("trainer.step", attempted,
                                         jax.device_get(stats_dev),
                                         num_cfg)
                sync_ms = (time.perf_counter() - t_sync0) * 1e3
            wall_ms = (time.perf_counter() - t_step0) * 1e3
            fields = {"wall_ms": round(wall_ms, 3),
                      "place_ms": round(place_ms, 3),
                      "dispatch_ms": round(dispatch_ms, 3),
                      "path": self.last_path,
                      "graphs": self.last_step_graphs,
                      "fused": self._fused and not fallback}
            if self._guard is not None:
                # guard runs synced loss/grad-norm to host — free to report
                fields.update(loss=self.last_loss,
                              grad_norm=self.last_grad_norm,
                              rolled_back=rolled_back,
                              device_wait_ms=round(sync_ms, 3))
            _tele.emit("train.step", step=attempted, **fields)
            # the goodput ledger folds the SAME timings into the run's
            # wall-clock attribution vector (compute/collective via the
            # guard's sync, one-off compile, host remainder; a rollback
            # reclassifies the discarded since-snapshot steps as waste)
            from ..telemetry import goodput as _goodput
            if _goodput.enabled():
                _goodput.note_step(
                    step=attempted, wall_ms=wall_ms,
                    device_wait_ms=(sync_ms if self._guard is not None
                                    else 0.0),
                    compile_ms=(dispatch_ms if (new_sig or retuned_now)
                                else 0.0),
                    rolled_back=rolled_back,
                    rollback_to=(self._t if rolled_back else None))
            # one "step" frame + its segments on the profiler timeline —
            # the raw material of profiler.step_report()'s host-gap
            # attribution (all from the timings measured above, so the
            # event fields and the span trace can never disagree)
            _prof.record_span("step.place", place_ms, parent="step",
                              step=attempted, t0=t_place0)
            _prof.record_span("step.dispatch", dispatch_ms, parent="step",
                              step=attempted, t0=t_disp0)
            if self._guard is not None:
                # the guard's loss/grad-norm device_get is the one point
                # the host provably blocks on the device inside the step
                _prof.record_span("step.device_wait", sync_ms,
                                  parent="step", step=attempted, t0=t_sync0)
            _prof.record_span("step", wall_ms, kind="frame",
                              step=attempted, t0=t_step0)
        self._m_steps.inc()
        self._m_step_ms.observe(wall_ms)
        if self._guard is not None and self.last_grad_norm is not None:
            self._m_gnorm.set(self.last_grad_norm)
        self._optimizer.num_update = self._t
        if not rolled_back:
            # aux effects (batchnorm running stats etc.) of a rolled-back
            # step are part of the bad step — dropping them keeps the
            # restored state internally consistent
            for (p, ectx), val in zip(self._info.get("effects", ()),
                                      effects):
                p._deposit_aux(val._data if isinstance(val, NDArray)
                               else val,
                               ectx if ectx is not None else self._ctx)
        return NDArray(loss, ctx=self._ctx)

    # ------------------------------------------------------------------
    # fault tolerance (mx.fault wiring)
    # ------------------------------------------------------------------
    @staticmethod
    def _chaos_batch(batch, n_data: int):
        """Chaos hook: when the active monkey draws ``nan_batch``, the first
        float data argument is replaced with NaNs — the realistic NaN-step
        signature (propagates to loss and every grad through the unmodified
        compiled graph). The ``grad_blowup`` / ``activation_drift`` knobs
        apply the monkey's seeded per-site scale ramp to the float data
        arguments instead: activations and gradients grow monotonically
        step over step — the slow divergence trajectory the numerics
        drift watchdog must flag BEFORE anything goes non-finite (the
        ramp eventually overflows f32 and the classic guard trips, so
        one chaos run exercises the whole warn → drift → non-finite
        escalation ladder)."""
        from ..fault import inject as _inject
        scale = (_inject.scale_ramp("grad_blowup")
                 * _inject.scale_ramp("activation_drift"))
        nan = _inject.should("nan_batch")
        if not nan and scale == 1.0:
            return batch
        out = list(batch)
        poisoned = False
        for i in range(n_data):
            a = out[i]
            v = a.asnumpy() if isinstance(a, NDArray) else onp.asarray(a)
            if v.dtype.kind != "f":
                continue
            if nan and not poisoned:
                out[i] = _inject.poison(v)
                poisoned = True
            elif scale != 1.0:
                out[i] = (v * scale).astype(v.dtype, copy=False)
        return tuple(out)

    def _maybe_snapshot(self) -> None:
        """Refresh the rollback snapshot (device-side copies — step-time
        donation consumes the live buffers, so rollback needs its own)."""
        g = self._guard
        if self._snapshot is not None \
                and self._t - self._snapshot[0] < g.snapshot_every:
            return
        self._snapshot = (self._t, self._copy_state(self._param_vals),
                          self._copy_state(self._opt_states))

    @staticmethod
    def _copy_state(tree):
        return jax.tree.map(lambda a: a.copy(), tree)

    def _apply_guard(self, loss, gnorm, ok=None, stats_dev=None,
                     step=None) -> bool:
        """Returns True when the step was rolled back. ``ok`` is the
        fused step's in-graph finite verdict — everything comes back in
        ONE host sync (``stats_dev``, the decimated numerics outputs,
        joins that same sync when due). Without ``ok`` (unfused/fallback
        path) the finite check is the PR-2-era SEPARATE jitted
        reduction, one more graph on this step's dispatch count.

        Escalation ordering: a real non-finite/limit verdict always
        wins; otherwise a sustained ``numerics.drift`` verdict (under
        ``MXTPU_NUMERICS_DRIFT=rollback``) feeds the SAME guard policy
        — so the drift watchdog can skip-and-rollback a diverging run
        steps before it ever goes non-finite, and ``halt``/
        ``max_consecutive`` precedence is unchanged."""
        g = self._guard
        stats_host = None
        if ok is None:
            from ..fault.guards import all_finite
            self.last_step_graphs += 1
            finite = all_finite(loss, gnorm)
            if stats_dev is not None:
                lf, gn, stats_host = jax.device_get((loss, gnorm,
                                                     stats_dev))
                lf, gn = float(lf), float(gn)
            else:
                lf = float(jax.device_get(loss))
                gn = float(jax.device_get(gnorm))
        elif stats_dev is not None:
            lf, gn, okv, stats_host = jax.device_get(
                (loss, gnorm, ok, stats_dev))
            lf, gn, finite = float(lf), float(gn), bool(okv)
        else:
            lf, gn, okv = jax.device_get((loss, gnorm, ok))
            lf, gn, finite = float(lf), float(gn), bool(okv)
        self.last_grad_norm = gn
        self.last_loss = lf
        drift = []
        if stats_host is not None:
            from ..telemetry import numerics as _numerics
            drift = _numerics.record("trainer.step", step, stats_host,
                                     self._numerics_cfg)
        reason = g.is_bad(finite, gn)
        if reason is None and drift \
                and self._numerics_cfg.drift_action == "rollback":
            # the drift watchdog armed the guard: escalate BEFORE any
            # non-finite exists, through the guard's own policy ladder
            v = drift[0]
            reason = (f"numerics drift at {v['site']} "
                      f"({v['reason']})")
        if reason is None:
            g.good_step()
            return False
        action = g.decide(self._t, reason,
                          detail=f"loss={lf:g}, grad_norm={gn:g}")
        if action == "rollback":
            self._m_rollbacks.inc()
            snap_t, pvals, states = self._snapshot
            # restore COPIES — the snapshot must survive further rollbacks
            # until the next good-step refresh
            self._param_vals = self._copy_state(pvals)
            self._opt_states = self._copy_state(states)
            self._t = snap_t
            self._t_dev = None
            self._optimizer.num_update = snap_t
            return True
        return False

    @property
    def guard(self):
        return self._guard

    @property
    def watchdog(self):
        return self._watchdog

    # ------------------------------------------------------------------
    def sync_to_block(self) -> None:
        """Write current sharded values back into the gluon Parameters."""
        if self._params is None:
            return
        for p, v in zip(self._params, self._param_vals):
            p.set_data(NDArray(jax.device_get(v), ctx=self._ctx))

    def save_states(self, fname: str, backend: str = "pickle") -> None:
        """Checkpoint parameters + optimizer state + step counter.

        ``backend='pickle'`` (default: one host-side file, reference
        Trainer.save_states shape) or ``'orbax'`` (a DIRECTORY written by
        orbax/TensorStore — each shard saved from its own device without a
        full host gather, the multi-controller-safe path SURVEY §5.4's TPU
        mapping prescribes). Opt-in, so existing extension-less paths keep
        producing a single pickle file; ``load_states`` auto-detects either.
        """
        if backend == "orbax":
            self._save_states_orbax(fname)
            return
        if backend != "pickle":
            raise MXNetError(f"unknown checkpoint backend {backend!r}")
        import pickle
        state = {
            "t": self._t,
            "opt_states": jax.device_get(self._opt_states),
            "param_vals": jax.device_get(self._param_vals),
        }
        tmp = f"{fname}.tmp-{os.getpid()}"
        try:
            with open(tmp, "wb") as f:
                pickle.dump(state, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, fname)  # never leave a truncated checkpoint
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    def _ckpt_tree(self):
        return {"param_vals": list(self._param_vals),
                "opt_states": [list(s) for s in self._opt_states]}

    def _save_states_orbax(self, path: str) -> None:
        try:
            import orbax.checkpoint as ocp
        except ImportError as e:
            raise MXNetError(
                "backend='orbax' needs the orbax-checkpoint package") from e
        path = os.path.abspath(path)
        with ocp.Checkpointer(ocp.CompositeCheckpointHandler()) as ckptr:
            ckptr.save(
                path,
                args=ocp.args.Composite(
                    state=ocp.args.PyTreeSave(self._ckpt_tree()),
                    meta=ocp.args.JsonSave({"t": self._t})),
                force=True)

    def load_states(self, fname: str, backend: str = "auto") -> None:
        if self._params is None:
            raise MXNetError("call step() once (or _init_state) before "
                             "load_states so the parameter set exists")
        if backend == "auto":
            backend = "orbax" if os.path.isdir(fname) else "pickle"
        if backend == "orbax":
            self._load_states_orbax(fname)
            return
        if backend != "pickle":
            raise MXNetError(f"unknown checkpoint backend {backend!r}")
        import pickle
        with open(fname, "rb") as f:
            state = pickle.load(f)
        self._t = state["t"]
        self._t_dev = None  # re-materialized from self._t on next step
        items = sorted(self._block.collect_params().items())
        vals, states = [], []
        for i, ((name, p), v, st) in enumerate(
                zip(items, state["param_vals"], state["opt_states"])):
            # Restore onto the EXACT live placements (guaranteed present:
            # load_states requires an initialized trainer, and _init_state
            # always records them) — keeps the traced step signature, incl.
            # the zero1 dp-partition of optimizer states.
            vals.append(jax.device_put(jnp.asarray(v),
                                       self._param_shardings[i]))
            states.append(tuple(
                jax.device_put(jnp.asarray(s), ssh)
                for s, ssh in zip(st, self._state_shardings[i])))
        self._param_vals, self._opt_states = tuple(vals), tuple(states)

    # ------------------------------------------------------------------
    # resumable checkpoints (mx.fault.checkpoint — SURVEY §5.4 + ISSUE 2)
    # ------------------------------------------------------------------
    _CKPT_FORMAT = 1

    def save_checkpoint(self, root: str, keep: Optional[int] = 3,
                        data_state: Optional[dict] = None) -> str:
        """Write one atomic, versioned checkpoint directory under ``root``
        covering EVERYTHING a bit-identical resume needs: parameters,
        optimizer state (incl. ZeRO-1 shards — gathered to host, resharded
        on load), the step counter, the LR-schedule position, and the RNG
        base key. Returns the checkpoint directory; retention keeps the
        newest ``keep`` steps. ``data_state`` (an
        ``io.PrefetchIter.shard_state()`` dict) rides in the meta so an
        elastic restore can resume the data stream under a new host count
        with no sample overlap. Call it from the training loop::

            if trainer.num_update % 500 == 0:
                trainer.save_checkpoint("ckpts/",
                                        data_state=it.shard_state())
        """
        if self._params is None:
            raise MXNetError("nothing to checkpoint: run step() at least "
                             "once so the parameter state exists")
        from ..fault import checkpoint as ckpt
        items = sorted(self._block.collect_params().items())
        arrays: Dict[str, Any] = {}
        for i, (name, _) in enumerate(items):
            arrays[f"param:{i:04d}"] = jax.device_get(self._param_vals[i])
            for j, s in enumerate(self._opt_states[i]):
                arrays[f"opt:{i:04d}:{j}"] = jax.device_get(s)
        if self._base_key is not None:
            arrays["rng:base_key"] = jax.device_get(
                jax.random.key_data(self._base_key))
        meta = {
            "trainer": "ShardedTrainer", "format": self._CKPT_FORMAT,
            "t": self._t,
            "num_update": self._optimizer.num_update,
            "lr": float(self._optimizer.learning_rate),
            "zero1": self._zero1,
            "optimizer": type(self._optimizer).__name__,
            "rng_impl": random_mod._impl(),
            "param_names": [name for name, _ in items],
            "opt_state_sizes": [len(s) for s in self._opt_states],
        }
        if data_state is not None:
            meta["data_state"] = dict(data_state)
        from . import elastic as _elastic
        idx, count = _elastic.membership()
        meta["elastic"] = {"generation": _elastic.generation(),
                           "process_count": count}
        return ckpt.save_checkpoint(root, arrays, meta, step=self._t,
                                    keep=keep)

    def restore_checkpoint(self, root: str,
                           step: Optional[int] = None) -> int:
        """Restore from the newest verified checkpoint under ``root`` (or
        an explicit ``step``), placing every array DIRECTLY onto its live
        mesh sharding (load → reshard; the zero1 dp-partition of optimizer
        states included). Requires an initialized trainer (one ``step()``
        — its state is fully overwritten). Returns the restored step."""
        if self._params is None:
            raise MXNetError("call step() once before restore_checkpoint "
                             "so the parameter set and shardings exist")
        from ..fault import checkpoint as ckpt
        if step is None:
            arrays, meta, step = ckpt.load_latest(root)
        else:
            arrays, meta, step = ckpt.load_checkpoint(root, step)
        if meta.get("trainer") != "ShardedTrainer" \
                or meta.get("format") != self._CKPT_FORMAT:
            raise MXNetError(
                f"checkpoint step {step} was not written by "
                f"ShardedTrainer.save_checkpoint (meta: {meta.get('trainer')!r}"
                f" format {meta.get('format')!r})")
        items = sorted(self._block.collect_params().items())
        names = [name for name, _ in items]
        saved_names = meta.get("param_names", [])
        if len(saved_names) != len(names):
            raise MXNetError(
                "checkpoint parameter set does not match this block: "
                f"saved {len(saved_names)} parameters, live {len(names)}")
        if saved_names != names:
            # auto-incremented gluon prefixes differ across same-process
            # instances; shapes/dtypes below are the binding contract
            import warnings
            warnings.warn(f"checkpoint parameter names differ from the live "
                          f"block ({saved_names[:2]}... vs {names[:2]}...); "
                          "restoring by position")
        vals, states = [], []
        for i in range(len(items)):
            try:
                v = arrays[f"param:{i:04d}"]
                st = [arrays[f"opt:{i:04d}:{j}"]
                      for j in range(meta["opt_state_sizes"][i])]
            except KeyError as e:
                raise MXNetError(f"checkpoint step {step} is missing "
                                 f"array {e}") from e
            live = self._param_vals[i]
            if tuple(v.shape) != tuple(live.shape) \
                    or jnp.asarray(v).dtype != live.dtype:
                raise MXNetError(
                    f"checkpoint array for parameter {names[i]!r} is "
                    f"{v.dtype}{tuple(v.shape)}, live parameter is "
                    f"{live.dtype}{tuple(live.shape)}")
            vals.append(jax.device_put(jnp.asarray(v),
                                       self._param_shardings[i]))
            states.append(tuple(
                jax.device_put(jnp.asarray(s), ssh)
                for s, ssh in zip(st, self._state_shardings[i])))
        self._param_vals, self._opt_states = tuple(vals), tuple(states)
        self._t = int(meta["t"])
        self._t_dev = None           # re-materialized from _t on next step
        self._optimizer.num_update = int(meta["num_update"])
        if "rng:base_key" in arrays:
            self._base_key = jax.random.wrap_key_data(
                jnp.asarray(arrays["rng:base_key"]),
                impl=meta.get("rng_impl") or random_mod._impl())
        self._snapshot = None        # stale rollback state from before
        # banked for elastic.recover: the data-shard boundary + the saving
        # membership live in the meta, not in any trainer array
        self.last_restore_meta = dict(meta)
        return step

    def _load_states_orbax(self, path: str) -> None:
        """Restore each array DIRECTLY onto its mesh sharding (TensorStore
        reads only this process's shards — no host-side full gather)."""
        try:
            import orbax.checkpoint as ocp
        except ImportError as e:
            raise MXNetError(
                "this checkpoint is an orbax directory; the orbax-checkpoint "
                "package is required to restore it") from e
        path = os.path.abspath(path)
        # restore targets: abstract arrays carrying the CURRENT shardings
        tpl = jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=a.sharding),
            self._ckpt_tree())
        with ocp.Checkpointer(ocp.CompositeCheckpointHandler()) as ckptr:
            restored = ckptr.restore(
                path,
                args=ocp.args.Composite(
                    state=ocp.args.PyTreeRestore(
                        tpl, restore_args=jax.tree.map(
                            lambda s: ocp.ArrayRestoreArgs(sharding=s.sharding),
                            tpl)),
                    meta=ocp.args.JsonRestore()))
        state = restored["state"]
        self._t = int(restored["meta"]["t"])
        self._t_dev = None
        self._param_vals = tuple(state["param_vals"])
        self._opt_states = tuple(tuple(s) for s in state["opt_states"])

"""Gluon block wrapping the expert-parallel switch FFN (see moe.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..gluon.block import HybridBlock

__all__ = ["MoEFFNBlock"]


class MoEFFNBlock(HybridBlock):
    """Switch-transformer FFN: router → top-1 expert → combine.

    Parameters carry a leading expert axis; shard it over ``ep`` with rule
    ``(r".*moe.*(w1|w2|b1|b2)", P("ep", ...))`` and the forward dispatches
    with one all_to_all each way when tracing under a mesh with ep>1;
    otherwise every expert runs locally (vmap-style einsum).
    """

    def __init__(self, num_experts: int, hidden: int, units: int,
                 capacity_factor: float = 1.25, **kw):
        super().__init__(**kw)
        self._E = num_experts
        self._cap_f = capacity_factor
        with self.name_scope():
            self.router = self.params.get("router", shape=(num_experts, units),
                                          init="xavier")
            self.w1 = self.params.get("w1", shape=(num_experts, hidden, units),
                                      init="xavier")
            self.b1 = self.params.get("b1", shape=(num_experts, hidden),
                                      init="zeros")
            self.w2 = self.params.get("w2", shape=(num_experts, units, hidden),
                                      init="xavier")
            self.b2 = self.params.get("b2", shape=(num_experts, units),
                                      init="zeros")

    def hybrid_forward(self, F, x, router=None, w1=None, b1=None, w2=None,
                       b2=None):
        from ..ndarray import NDArray
        xv = x._data if isinstance(x, NDArray) else x
        rv, w1v, b1v, w2v, b2v = (
            p._data if isinstance(p, NDArray) else p
            for p in (router, w1, b1, w2, b2))
        B, L, C = xv.shape
        T = B * L
        tokens = xv.reshape(T, C)
        gate = jnp.einsum("tc,ec->te", tokens.astype(jnp.float32),
                          rv.astype(jnp.float32))
        E = self._E
        from .mesh import current_active_mesh
        mesh = current_active_mesh()
        use_ep = (mesh is not None and mesh.shape.get("ep", 1) > 1
                  and isinstance(xv, jax.core.Tracer)
                  and E % mesh.shape["ep"] == 0
                  and T % mesh.shape["ep"] == 0)
        if use_ep:
            from functools import partial
            from jax.sharding import PartitionSpec as P
            from .collectives import shard_map
            from .moe import moe_ffn
            ep = mesh.shape["ep"]
            cap = max(1, int(self._cap_f * (T // ep) / E))
            pspec = {"w1": P("ep"), "b1": P("ep"), "w2": P("ep"),
                     "b2": P("ep")}
            fn = shard_map(partial(moe_ffn, capacity=cap, axis="ep"),
                           mesh=mesh,
                           in_specs=(pspec, P("ep"), P("ep")),
                           out_specs=P("ep"))
            out = fn({"w1": w1v, "b1": b1v, "w2": w2v, "b2": b2v},
                     tokens, gate)
        else:
            # single-shard switch FFN: same routing semantics and the same
            # capacity formula as the ep path (cap_f·T/E per expert) so the
            # dispatch buffer stays O(cap_f·T·C), not O(E·T·C)
            from .moe import moe_dispatch
            cap = min(T, max(1, int(self._cap_f * T / E)))
            d, combine, eidx, pos, keep = moe_dispatch(tokens, gate, E, cap)
            h = jnp.einsum("ekc,ehc->ekh", d, w1v,
                           preferred_element_type=jnp.float32)
            h = jax.nn.relu(h + b1v[:, None, :])
            y = jnp.einsum("ekh,ech->ekc", h.astype(d.dtype), w2v,
                           preferred_element_type=jnp.float32).astype(d.dtype)
            y = y + b2v[:, None, :]
            out = y[eidx, jnp.where(keep, pos, 0)]
            out = jnp.where(keep[:, None], out, 0.0)
            out = out * combine[:, None].astype(y.dtype)
        out = out.reshape(B, L, C)
        return NDArray(out, ctx=x.context) if isinstance(x, NDArray) else out

"""Elastic multi-host control plane — explicit membership over leases.

Reference counterpart: the dmlc tracker + ps-lite heartbeats
(``3rdparty/ps-lite``'s ``Van::Heartbeat`` / scheduler timeout), which
this repo's collective rebuild of the distributed stack (SURVEY §2.5)
deliberately dropped — and with it the one thing the parameter server
did better than a bare SPMD pod: *noticing* that a worker died. In the
multi-controller JAX model a lost host does not produce an error; the
survivors block inside the next collective forever. This module puts
the membership signal back, on the transport the runtime already trusts
for control-plane exchange (the jax coordination-service key-value
store that :func:`telemetry.collective_ledger.crosscheck` uses):

- **Leases** — every process banks a heartbeat lease under
  ``mxtpu/elastic/<generation>/lease/<index>`` every
  ``MXTPU_ELASTIC_HEARTBEAT_S`` seconds (default: a third of the lease).
  The write is an overwrite of the process's own key — never a
  collective, never blocking on a peer.
- **Detection** — the lease watchdog (a daemon thread started by
  ``dist.initialize`` when ``MXTPU_ELASTIC=1``) scans the lease table
  each beat. A peer whose newest lease is older than
  ``MXTPU_ELASTIC_LEASE_S`` is a *detected host loss*: the watchdog
  trips, every surviving host writes one flight bundle stamped with the
  dead process index, and :func:`poll` (hooked into
  ``ShardedTrainer.step``) raises :class:`HostLossError` at the next
  step boundary — a loud, attributable failure instead of a hung
  collective.
- **Recovery** — :func:`recover` is the survivor-side restart:
  ``fault.checkpoint.load_latest`` through
  ``ShardedTrainer.restore_checkpoint`` (which re-places host arrays
  onto the *live* mesh shardings, so the ZeRO-1 opt-state partition and
  the RNG base key reshard to the new host count — PR 9's
  cross-mesh-shape resume generalized from a test into the recovery
  path), plus the checkpointed ``io.PrefetchIter`` shard boundary so
  per-host data sharding survives the membership change without sample
  overlap. Each recovery bumps the **restore generation** counter
  (``MXTPU_ELASTIC_GENERATION`` seeds it across process restarts), which
  namespaces the lease keys so a restarted pod never reads a dead
  generation's leases.

Everything is off by default (``MXTPU_ELASTIC`` unset): the trainer
hook is one :func:`enabled` read, and without a coordination client the
control plane degrades to a single-member pod. The transport is
pluggable (:class:`LocalTransport`) so the detection state machine is
an ordinary unit test — the same philosophy as ``fault.inject``.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ..base import MXNetError
from ..lockcheck import make_lock

__all__ = ["HostLossError", "LocalTransport", "enabled", "configure",
           "lease_s", "heartbeat_s", "generation", "membership",
           "start", "stop", "active", "beat", "check", "poll",
           "snapshot", "recover", "reset"]

_LEASE_PREFIX = "mxtpu/elastic"

_LOCK = make_lock("elastic._LOCK")
_ON_OVERRIDE: Optional[bool] = None
_LEASE_OVERRIDE: Optional[float] = None
_BEAT_OVERRIDE: Optional[float] = None
_TRANSPORT_OVERRIDE: Optional[Any] = None


def _new_state() -> Dict[str, Any]:
    return {
        "thread": None,          # the heartbeat/watchdog daemon
        "stop": None,            # its threading.Event
        "started_at": None,      # perf-independent wall anchor for grace
        "beats": 0,              # leases banked by THIS process
        "stalled_beats": 0,      # beats skipped by the host_stall chaos
        "lost": set(),           # detected-dead process indices
        "pending": [],           # losses poll() has not raised yet
        "bundled": set(),        # indices already stamped into a bundle
        "leases": {},            # last scanned lease table (idx -> doc)
        "last_scan": None,       # wall clock of the last check()
        "recoveries": 0,         # recover() calls in THIS process
    }


_S = _new_state()


class HostLossError(MXNetError):
    """A pod member's lease expired — detected host loss. Carries the
    dead process indices and the membership generation, so the handler
    (or the launcher reading the message) can restart the survivors
    from the last checkpoint instead of hanging in a collective."""

    def __init__(self, lost: List[int], generation: int, lease: float):
        self.lost = sorted(int(p) for p in lost)
        self.generation = int(generation)
        super().__init__(
            f"elastic: host loss detected — process(es) "
            f"{self.lost} missed the {lease:g}s heartbeat lease "
            f"(membership generation {self.generation}). Surviving hosts "
            "wrote flight bundles stamped with the dead index; restart "
            "the run on the survivors and restore with "
            "fault.checkpoint.load_latest (elastic.recover).")


# ---------------------------------------------------------------------------
# knobs
# ---------------------------------------------------------------------------

def enabled() -> bool:
    """Elastic control plane on? One env read (``MXTPU_ELASTIC=1``;
    :func:`configure` overrides)."""
    if _ON_OVERRIDE is not None:
        return _ON_OVERRIDE
    return os.environ.get("MXTPU_ELASTIC", "0") == "1"


def lease_s() -> float:
    """Lease validity window (``MXTPU_ELASTIC_LEASE_S``, default 10):
    a peer whose newest lease is older than this is a detected loss."""
    if _LEASE_OVERRIDE is not None:
        return _LEASE_OVERRIDE
    try:
        return max(0.1, float(os.environ.get("MXTPU_ELASTIC_LEASE_S",
                                             "10")))
    except ValueError:
        return 10.0


def heartbeat_s() -> float:
    """Beat interval (``MXTPU_ELASTIC_HEARTBEAT_S``; default a third of
    the lease, floor 0.05s) — three missed beats expire a lease."""
    if _BEAT_OVERRIDE is not None:
        return _BEAT_OVERRIDE
    raw = os.environ.get("MXTPU_ELASTIC_HEARTBEAT_S", "")
    if raw:
        try:
            return max(0.05, float(raw))
        except ValueError:
            pass
    return max(0.05, lease_s() / 3.0)


def generation() -> int:
    """The restore-generation counter: ``MXTPU_ELASTIC_GENERATION``
    (stamped by the launcher on each elastic restart) plus the in-process
    :func:`recover` count. Namespaces the lease keys, rides checkpoint
    meta, and is the postmortem's "how many times has this run come back
    from the dead" number."""
    try:
        base = int(os.environ.get("MXTPU_ELASTIC_GENERATION", "0"))
    except ValueError:
        base = 0
    with _LOCK:
        return base + _S["recoveries"]


def configure(on: Optional[bool] = None,
              lease: Optional[float] = None,
              heartbeat: Optional[float] = None,
              transport: Optional[Any] = None) -> None:
    """Programmatic override of the env knobs and (for tests/drills) the
    lease transport. Calling with no arguments clears every override."""
    global _ON_OVERRIDE, _LEASE_OVERRIDE, _BEAT_OVERRIDE, \
        _TRANSPORT_OVERRIDE
    if on is None and lease is None and heartbeat is None \
            and transport is None:
        _ON_OVERRIDE = _LEASE_OVERRIDE = _BEAT_OVERRIDE = None
        _TRANSPORT_OVERRIDE = None
        return
    if on is not None:
        _ON_OVERRIDE = bool(on)
    if lease is not None:
        _LEASE_OVERRIDE = max(0.1, float(lease))
    if heartbeat is not None:
        _BEAT_OVERRIDE = max(0.01, float(heartbeat))
    if transport is not None:
        _TRANSPORT_OVERRIDE = transport


# ---------------------------------------------------------------------------
# transports
# ---------------------------------------------------------------------------

class _KVTransport:
    """The production transport: the jax coordination-service KV store
    (the same client ``collective_ledger.crosscheck`` exchanges digest
    tables over). Lease refreshes overwrite the process's own key;
    scans are non-blocking directory reads — absence is data, never a
    hang."""

    def __init__(self, client, index: int, count: int):
        self._client = client
        self.index = int(index)
        self.count = int(count)

    def put(self, key: str, value: str) -> None:
        try:
            self._client.key_value_set(key, value, allow_overwrite=True)
        except TypeError:
            # older coordination clients lack allow_overwrite: emulate
            # the refresh as delete-then-set (only this process ever
            # writes its own lease key, so the window is benign)
            try:
                self._client.key_value_delete(key)
            except Exception:  # noqa: BLE001 — first write has no key
                pass
            self._client.key_value_set(key, value)

    def scan(self, prefix: str) -> Dict[str, str]:
        try:
            return dict(self._client.key_value_dir_get(prefix))
        except Exception:  # noqa: BLE001 — empty dir raises on some builds
            return {}


class LocalTransport:
    """Dict-backed transport simulating an N-process pod inside one
    process (unit tests, the detection-state-machine drills). Share one
    ``store`` dict across N instances, one per simulated process."""

    def __init__(self, store: Optional[Dict[str, str]] = None,
                 index: int = 0, count: int = 1):
        self.store = store if store is not None else {}
        self.index = int(index)
        self.count = int(count)

    def put(self, key: str, value: str) -> None:
        self.store[key] = value

    def scan(self, prefix: str) -> Dict[str, str]:
        return {k: v for k, v in self.store.items()
                if k.startswith(prefix)}


def _transport() -> Optional[Any]:
    """The active transport: a configured override, else the live
    coordination client, else None (single-member pod)."""
    if _TRANSPORT_OVERRIDE is not None:
        return _TRANSPORT_OVERRIDE
    from ..telemetry.collective_ledger import _coord
    client, idx, n = _coord()
    if client is None:
        return None
    return _KVTransport(client, idx, n)


def membership() -> Tuple[int, int]:
    """``(process_index, process_count)`` as the control plane sees it."""
    t = _transport()
    if t is None:
        from ..telemetry.collective_ledger import _coord
        _, idx, n = _coord()
        return idx, n
    return t.index, t.count


def _lease_key_prefix(gen: int) -> str:
    return f"{_LEASE_PREFIX}/{gen}/lease/"


# ---------------------------------------------------------------------------
# heartbeats + detection
# ---------------------------------------------------------------------------

def beat(step: Optional[int] = None) -> bool:
    """Bank one heartbeat lease for this process (overwrite of its own
    key). Returns False when there is nothing to bank (no transport, or
    the seeded ``host_stall`` chaos knob is holding the beat back while
    the process keeps running — the nastier failure mode the lease
    watchdog must catch). The payload carries the per-host goodput
    collective share, so the lease table doubles as the straggler gauge:
    a slow host is visible in its peers' membership snapshot *before*
    it becomes a failure."""
    t = _transport()
    if t is None:
        return False
    from ..fault import inject as _inject
    if _inject.heartbeat_stalled():
        with _LOCK:
            _S["stalled_beats"] += 1
        return False
    from ..telemetry import goodput as _goodput
    from ..telemetry.export import dumps_strict
    with _LOCK:
        _S["beats"] += 1
        n_beats = _S["beats"]
    doc = {"t": time.time(), "step": step, "beats": n_beats,
           "pid": os.getpid(), "generation": generation(),
           "collective_ms": round(_goodput.collective_ms(), 3)}
    try:
        t.put(_lease_key_prefix(generation()) + str(t.index),
              dumps_strict(doc, sort_keys=True))
    except Exception as e:  # noqa: BLE001 — a dying KV store must not
        import warnings     # kill the beater before detection can run
        warnings.warn(f"[elastic] lease write failed: {e}")
        return False
    return True


def check(raise_on_loss: bool = True,
          now: Optional[float] = None) -> Dict[str, Any]:
    """Scan the lease table and classify every pod member. Returns the
    membership snapshot; on newly expired peers the watchdog trips —
    one ``elastic.host_loss`` event + one flight bundle per dead index
    per surviving process — and raises :class:`HostLossError` unless
    ``raise_on_loss=False`` (the daemon thread's mode: it records the
    loss for :func:`poll` to surface at the next step boundary)."""
    t = _transport()
    if t is None or t.count <= 1:
        return snapshot()
    from ..telemetry.export import loads_strict
    now = time.time() if now is None else now
    lease = lease_s()
    raw = t.scan(_lease_key_prefix(generation()))
    table: Dict[int, Dict[str, Any]] = {}
    for key, blob in raw.items():
        try:
            idx = int(key.rsplit("/", 1)[-1])
            doc = loads_strict(blob)
        except (ValueError, TypeError):
            continue
        doc["age_s"] = round(max(now - float(doc.get("t") or 0.0), 0.0), 3)
        table[idx] = doc
    with _LOCK:
        started = _S["started_at"]
        _S["leases"] = table
        _S["last_scan"] = now
        fresh: List[int] = []
        for p in range(t.count):
            if p == t.index or p in _S["lost"]:
                continue
            ent = table.get(p)
            if ent is None:
                # a peer that never banked: grace-period it from the
                # watchdog's own start, so a slow rendezvous is not a
                # false positive
                if started is not None and now - started > lease:
                    fresh.append(p)
                continue
            if ent["age_s"] > lease:
                fresh.append(p)
        _S["lost"].update(fresh)
        _S["pending"].extend(fresh)
    if fresh:
        _trip(fresh)
    if raise_on_loss:
        poll()
    return snapshot()


def _trip(lost: List[int]) -> None:
    """The detection path: event + counter + one flight bundle per dead
    process index (stamped with it), exactly once per index per
    surviving process — a crash loop re-detecting the same corpse must
    not storm the recorder."""
    from ..telemetry import events as _events
    from ..telemetry import flight as _flight
    from ..telemetry import metrics as _metrics
    snap = snapshot()
    for p in sorted(lost):
        _events.emit("elastic.host_loss", severity="error",
                     lost_process=p, generation=snap["generation"],
                     lease_s=snap["lease_s"])
        _metrics.counter("mxtpu_elastic_host_loss_total",
                         "Detected host losses (expired leases)").inc()
        with _LOCK:
            first = p not in _S["bundled"]
            _S["bundled"].add(p)
        if first:
            _flight.dump("host_loss", site="elastic.check",
                         lost_process=p, membership=snap)


def poll() -> None:
    """The trainer-hot-path hook: raise :class:`HostLossError` iff the
    lease watchdog detected a loss since the last poll. One lock-free
    list read when nothing happened; never any I/O."""
    if not _S["pending"]:
        return
    with _LOCK:
        pending = list(_S["pending"])
        _S["pending"].clear()
    if pending:
        raise HostLossError(pending, generation(), lease_s())


# ---------------------------------------------------------------------------
# the heartbeat daemon
# ---------------------------------------------------------------------------

def start() -> bool:
    """Start the heartbeat/lease-watchdog daemon (idempotent). Banks the
    first lease synchronously so a peer scanning right after its own
    start sees us. No-op (False) when elastic is off or the pod has a
    single member."""
    if not enabled():
        return False
    t = _transport()
    if t is None or t.count <= 1:
        return False
    with _LOCK:
        th = _S["thread"]
        if th is not None and th.is_alive():
            return True
        _S["started_at"] = time.time()
        stop_ev = _S["stop"] = threading.Event()
    beat()

    def _run() -> None:
        while not stop_ev.wait(heartbeat_s()):
            try:
                beat()
                check(raise_on_loss=False)
            except Exception as e:  # noqa: BLE001 — the watchdog must
                import warnings     # outlive transient transport faults
                warnings.warn(f"[elastic] heartbeat tick failed: {e}")

    th = threading.Thread(target=_run, name="mx-elastic-heartbeat",
                          daemon=True)
    with _LOCK:
        _S["thread"] = th
    th.start()
    from ..telemetry import events as _events
    _events.emit("elastic.start", generation=generation(),
                 process_index=t.index, process_count=t.count,
                 lease_s=lease_s(), heartbeat_s=heartbeat_s())
    return True


def stop() -> None:
    """Stop the daemon (idempotent; ``dist.finalize`` calls this)."""
    with _LOCK:
        th, ev = _S["thread"], _S["stop"]
        _S["thread"] = _S["stop"] = None
    if ev is not None:
        ev.set()
    if th is not None and th.is_alive():
        th.join(timeout=2.0)


def active() -> bool:
    th = _S["thread"]
    return th is not None and th.is_alive()


# ---------------------------------------------------------------------------
# recovery
# ---------------------------------------------------------------------------

def recover(trainer, root: str, data_iter=None,
            step: Optional[int] = None) -> int:
    """The survivor-side restart: restore ``trainer`` from the newest
    complete checkpoint under ``root`` (``fault.checkpoint.load_latest``
    → :meth:`ShardedTrainer.restore_checkpoint`, which re-places every
    host array onto the live mesh shardings — the ZeRO-1 opt-state
    partition and the RNG base key reshard to the surviving host count),
    then restore the data iterator's host-shard boundary from the
    checkpoint meta under the NEW membership so the resumed stream
    overlaps no consumed sample. Bumps the restore generation and emits
    one ``elastic.restore`` event. Returns the restored step."""
    restored = trainer.restore_checkpoint(root, step=step)
    meta = getattr(trainer, "last_restore_meta", None) or {}
    if data_iter is not None and meta.get("data_state"):
        idx, count = membership()
        data_iter.restore_shard(meta["data_state"], index=idx,
                                count=count)
    with _LOCK:
        _S["recoveries"] += 1
        # a recovered pod is a new membership: dead indices from the old
        # generation must not poison the new lease table
        _S["lost"].clear()
        _S["pending"].clear()
        _S["bundled"].clear()
        _S["leases"] = {}
    gen = generation()
    from ..telemetry import events as _events
    from ..telemetry import metrics as _metrics
    idx, count = membership()
    _events.emit("elastic.restore", step=restored, generation=gen,
                 process_index=idx, process_count=count)
    _metrics.gauge("mxtpu_elastic_generation",
                   "Elastic restore generation").set(gen)
    return restored


# ---------------------------------------------------------------------------
# snapshot / reset
# ---------------------------------------------------------------------------

def snapshot() -> Dict[str, Any]:
    """The membership section of ``telemetry.snapshot()``, flight
    bundles, and ``tools/postmortem.py``: the lease table with last
    heartbeat ages, the elected primary, detected losses, and the
    restore generation counter."""
    idx, count = membership()
    # knobs resolve BEFORE the lock: generation() takes _LOCK itself
    on, act = enabled(), active()
    gen, lease, hb = generation(), lease_s(), heartbeat_s()
    with _LOCK:
        leases = {str(p): dict(doc) for p, doc in
                  sorted(_S["leases"].items())}
        lost = sorted(_S["lost"])
        doc = {
            "enabled": on,
            "active": act,
            "process": {"index": idx, "count": count},
            "generation": gen,
            "lease_s": lease,
            "heartbeat_s": hb,
            "beats": _S["beats"],
            "stalled_beats": _S["stalled_beats"],
            "leases": leases,
            "lost": lost,
            "last_scan": _S["last_scan"],
            # the elected primary under membership change: the lowest
            # surviving index (process 0 unless it is the corpse)
            "elected": next((p for p in range(count) if p not in lost),
                            0),
        }
    return doc


def reset() -> None:
    """Stop the daemon and drop all state including overrides (tests)."""
    global _S
    stop()
    with _LOCK:
        _S = _new_state()
    configure()

"""Named device meshes.

Reference counterpart: the *topology* side of the kvstore backends — the GPU
tree in ``src/kvstore/comm_tree.h (CommDeviceTree)`` and ps-lite's
scheduler/server/worker role map (``3rdparty/ps-lite/src/postoffice.cc``).
On TPU the topology is a first-class compiler input: a
:class:`jax.sharding.Mesh` whose named axes carry the parallelism meaning.

Axis convention (all optional, size-1 axes are free):

======  =======================================
``dp``  data parallelism (batch dim)
``tp``  tensor/model parallelism (hidden dims)
``pp``  pipeline parallelism (layer stages)
``sp``  sequence/context parallelism (ring attention)
``ep``  expert parallelism (MoE expert dim)
======  =======================================

Collectives ride ICI when the mesh is built from
``mesh_utils.create_device_mesh`` (which lays contiguous axes onto the torus)
and DCN across slices — the "collectives ride ICI, not DCN" rule is encoded
by putting ``dp`` outermost (slowest/DCN-most) and ``tp``/``sp`` innermost.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as onp
from jax.sharding import Mesh

AXIS_DP = "dp"
AXIS_TP = "tp"
AXIS_PP = "pp"
AXIS_SP = "sp"
AXIS_EP = "ep"

#: canonical outer→inner ordering: dp over DCN/outer ICI, tp/sp innermost
#: (highest-bandwidth ICI neighbours), matching the scaling-book recipe.
CANONICAL_ORDER = (AXIS_DP, AXIS_PP, AXIS_EP, AXIS_SP, AXIS_TP)

_DEFAULT: List[Optional[Mesh]] = [None]


@dataclass
class MeshConfig:
    """Declarative mesh spec. Unset axes default to 1; one axis may be -1
    meaning "all remaining devices" (like a reshape wildcard)."""

    dp: int = -1
    tp: int = 1
    pp: int = 1
    sp: int = 1
    ep: int = 1

    def resolve(self, n_devices: int) -> Dict[str, int]:
        sizes = {AXIS_DP: self.dp, AXIS_TP: self.tp, AXIS_PP: self.pp,
                 AXIS_SP: self.sp, AXIS_EP: self.ep}
        wild = [k for k, v in sizes.items() if v == -1]
        if len(wild) > 1:
            raise ValueError(f"only one axis may be -1, got {wild}")
        known = 1
        for k, v in sizes.items():
            if v != -1:
                if v <= 0:
                    raise ValueError(f"axis {k} must be positive or -1, got {v}")
                known *= v
        if wild:
            if n_devices % known:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes product {known}")
            sizes[wild[0]] = n_devices // known
        else:
            if known != n_devices:
                raise ValueError(
                    f"mesh axes product {known} != device count {n_devices}")
        return sizes


def make_mesh(config: Optional[MeshConfig] = None,
              devices: Optional[Sequence] = None, **axes) -> Mesh:
    """Build a named Mesh. ``make_mesh(dp=2, tp=4)`` or with a MeshConfig.

    Axes are laid out in :data:`CANONICAL_ORDER`; on real TPU slices the
    device order comes from ``mesh_utils.create_device_mesh`` so inner axes
    land on ICI neighbours.
    """
    if config is None:
        config = MeshConfig(**{**dict(dp=-1), **axes}) if axes else MeshConfig()
    devices = list(devices if devices is not None else jax.devices())
    sizes = config.resolve(len(devices))
    shape = tuple(sizes[a] for a in CANONICAL_ORDER)
    try:
        from jax.experimental import mesh_utils
        dev_array = mesh_utils.create_device_mesh(shape, devices=devices)
    except Exception:
        dev_array = onp.array(devices).reshape(shape)
    return Mesh(dev_array, CANONICAL_ORDER)


def local_mesh(**axes) -> Mesh:
    """Mesh over this process's addressable devices only."""
    return make_mesh(devices=jax.local_devices(), **axes)


def default_mesh() -> Mesh:
    """The process-wide mesh (lazily a pure-DP mesh over all devices)."""
    if _DEFAULT[0] is None:
        _DEFAULT[0] = make_mesh()
    return _DEFAULT[0]


def set_default_mesh(mesh: Optional[Mesh]) -> None:
    _DEFAULT[0] = mesh


def axis_size(mesh: Mesh, axis: str) -> int:
    return mesh.shape[axis] if axis in mesh.shape else 1


# ---------------------------------------------------------------------------
# Active compute mesh: bound while a ShardedTrainer step (or any mesh-aware
# computation) is being TRACED, so ops can emit mesh-native collectives —
# e.g. dot_product_attention lowering to ring attention over ``sp``.
# ---------------------------------------------------------------------------
import threading as _threading

_ACTIVE = _threading.local()


class active_mesh:
    """Context manager binding the mesh visible to mesh-aware ops."""

    def __init__(self, mesh: Optional[Mesh]):
        self._mesh = mesh

    def __enter__(self):
        stack = getattr(_ACTIVE, "stack", None)
        if stack is None:
            stack = _ACTIVE.stack = []
        stack.append(self._mesh)
        return self._mesh

    def __exit__(self, *exc):
        _ACTIVE.stack.pop()


def current_active_mesh() -> Optional[Mesh]:
    stack = getattr(_ACTIVE, "stack", None)
    return stack[-1] if stack else None

"""Pipeline parallelism over the ``pp`` mesh axis.

Reference counterpart: none — the reference scales by data parallelism only
(SURVEY §2.5 names pp as a parity-plus extension). TPU-native design: a
GPipe-style microbatched schedule expressed FUNCTIONALLY — stage parameters
carry a leading ``(n_stages, ...)`` axis sharded over ``pp``; inside
``shard_map`` each device applies its stage and activations hop to the next
stage with ``lax.ppermute`` (one ICI neighbour hop per tick). The schedule is
a ``lax.scan`` over ``n_micro + n_stages - 1`` ticks, so reverse-mode
autodiff derives the backward pipeline automatically (the transposed
schedule) — no hand-written 1F1B state machine to maintain, which is the
whole point of building on a functional IR.

Bubble fraction is the GPipe ``(S-1)/(M+S-1)``; pick ``n_micro >= 4·S``.
"""
from __future__ import annotations

from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from .collectives import shard_map

P = PartitionSpec

__all__ = ["pipeline_apply", "pipeline_sharded"]


def pipeline_apply(stage_params, x, stage_fn: Callable, axis: str = "pp",
                   n_micro: Optional[int] = None):
    """Microbatched pipeline forward; call INSIDE shard_map with ``axis``
    bound.

    ``stage_params``: pytree whose leaves have a leading stage axis of LOCAL
    size 1 (the ``pp`` shard of a ``(n_stages, ...)`` stack).
    ``x``: (n_micro, mb, ...) microbatched input, replicated over ``axis``.
    ``stage_fn(params, xmb) -> ymb``: one stage's computation on one
    microbatch (input/output shapes must match — inter-stage activations
    ride one fixed-shape buffer).

    Returns (n_micro, mb, ...) outputs, replicated over ``axis`` (each tick
    the last stage's finished microbatch enters a result buffer; the buffer
    is psum-broadcast at the end).
    """
    n_stages = lax.psum(1, axis)
    idx = lax.axis_index(axis)
    local = jax.tree.map(lambda p: p[0], stage_params)
    M = x.shape[0] if n_micro is None else n_micro
    T = M + n_stages - 1
    perm_fwd = [(i, i + 1) for i in range(n_stages - 1)]
    buf0 = jnp.zeros_like(x[0])
    ys0 = jnp.zeros_like(x)

    def tick(carry, t):
        buf, ys = carry
        # stage 0 ingests microbatch t (clamped; beyond M the result is
        # never written), later stages consume the hopped-in activation
        xin = jnp.where(idx == 0, x[jnp.minimum(t, M - 1)], buf)
        y = stage_fn(local, xin)
        done = t - (n_stages - 1)
        write = (idx == n_stages - 1) & (done >= 0)
        ys = lax.cond(
            write,
            lambda ys: lax.dynamic_update_index_in_dim(
                ys, y, jnp.maximum(done, 0), 0),
            lambda ys: ys, ys)
        buf = lax.ppermute(y, axis, perm_fwd)
        return (buf, ys), None

    (_, ys), _ = lax.scan(tick, (buf0, ys0), jnp.arange(T))
    # broadcast the last stage's result buffer to every stage
    ys = lax.psum(jnp.where(idx == n_stages - 1, ys, jnp.zeros_like(ys)),
                  axis)
    return ys


def pipeline_sharded(mesh: Mesh, stage_params, x, stage_fn: Callable,
                     n_micro: int, axis: str = "pp",
                     batch_axis: Optional[str] = None):
    """Host-level entry: ``stage_params`` leaves are global
    ``(n_stages, ...)`` stacks (sharded over ``axis``); ``x`` is a global
    (batch, ...) array, reshaped to (n_micro, batch/n_micro, ...).

    The microbatch dim stays replicated over ``axis``; ``batch_axis`` (e.g.
    ``"dp"``) additionally shards the within-microbatch batch dim, giving
    dp×pp hybrid parallelism from one entry point."""
    if x.shape[0] % n_micro:
        raise ValueError(f"batch {x.shape[0]} not divisible by "
                         f"n_micro {n_micro}")
    xm = x.reshape((n_micro, x.shape[0] // n_micro) + x.shape[1:])
    pspec = jax.tree.map(lambda _: P(axis), stage_params)
    xspec = P(None, batch_axis)
    fn = shard_map(
        partial(pipeline_apply, stage_fn=stage_fn, axis=axis),
        mesh=mesh,
        in_specs=(pspec, xspec),
        out_specs=xspec)
    params_sharded = jax.tree.map(
        lambda p: jax.device_put(p, NamedSharding(mesh, P(axis))),
        stage_params)
    xm = jax.device_put(xm, NamedSharding(mesh, xspec))
    out = jax.jit(fn)(params_sharded, xm)
    return out.reshape(x.shape[0:1] + out.shape[2:])

"""Collective verbs over mesh axes.

Reference counterpart: the NCCL verb surface in ``src/kvstore/kvstore_nccl.h``
(ncclAllReduce/ncclBcast) and the device-to-device reduce in
``src/kvstore/comm.h (CommDevice::Reduce/Broadcast)``. Here each verb is the
XLA collective primitive, usable inside ``shard_map``/``pjit`` regions where
the named axis is bound; XLA lowers them onto ICI rings/trees automatically
(the hand-written PCIe tree in comm_tree.h has no equivalent to maintain).
"""
from __future__ import annotations

from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec
try:
    from jax import shard_map as _raw_shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map as _raw_shard_map


def shard_map(f, mesh, in_specs, out_specs):
    """shard_map with replication checking off, across jax versions (the
    kwarg was renamed check_rep → check_vma)."""
    try:
        return _raw_shard_map(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, check_vma=False)
    except TypeError:
        return _raw_shard_map(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, check_rep=False)


P = PartitionSpec

__all__ = ["all_reduce", "all_gather", "reduce_scatter", "broadcast",
           "ppermute", "all_to_all", "axis_index", "axis_size", "psum_scatter"]


def all_reduce(x, axis: Union[str, Sequence[str]], op: str = "sum"):
    """In-shard_map all-reduce (``ncclAllReduce`` parity)."""
    if op == "sum":
        return lax.psum(x, axis)
    if op == "mean":
        return lax.pmean(x, axis)
    if op == "max":
        return lax.pmax(x, axis)
    if op == "min":
        return lax.pmin(x, axis)
    raise ValueError(f"unsupported reduce op {op!r}")


def all_gather(x, axis: Union[str, Sequence[str]], *, tiled: bool = True,
               gather_axis: int = 0):
    return lax.all_gather(x, axis, axis=gather_axis, tiled=tiled)


def reduce_scatter(x, axis: Union[str, Sequence[str]], *, scatter_axis: int = 0):
    return lax.psum_scatter(x, axis, scatter_dimension=scatter_axis, tiled=True)


psum_scatter = reduce_scatter


def broadcast(x, axis: str, root: int = 0):
    """Every shard takes the root shard's value (``ncclBcast`` parity)."""
    full = lax.all_gather(x, axis, axis=0, tiled=False)
    return full[root]


def ppermute(x, axis: str, perm: Sequence[tuple]):
    return lax.ppermute(x, axis, perm=perm)


def all_to_all(x, axis: str, split_axis: int, concat_axis: int):
    return lax.all_to_all(x, axis, split_axis=split_axis,
                          concat_axis=concat_axis, tiled=True)


def axis_index(axis: str):
    return lax.axis_index(axis)


def axis_size(axis: str):
    return lax.psum(1, axis)


# ----------------------------------------------------------------------
# Host-level convenience: run one collective over sharded arrays outside any
# traced region (the kvstore eager path uses these).
# ----------------------------------------------------------------------
def _reduce_fn(mesh: Mesh, axis: str, op: str, spec: PartitionSpec):
    key = (mesh, axis, op, spec)
    fn = _REDUCE_CACHE.get(key)
    if fn is None:
        fn = jax.jit(shard_map(lambda v: all_reduce(v, axis, op), mesh=mesh,
                               in_specs=(spec,), out_specs=spec))
        _REDUCE_CACHE[key] = fn
    return fn


_REDUCE_CACHE: dict = {}


def run_all_reduce(mesh: Mesh, x: jax.Array, axis: str = "dp", op: str = "sum",
                   spec: Optional[PartitionSpec] = None) -> jax.Array:
    """Eager all-reduce of a sharded array over ``axis``; other mesh axes
    pass through. ``spec`` is the array's PartitionSpec if known. Compiled
    executables are cached per (mesh, axis, op, spec) — the analog of the
    reference kvstore reusing its comm buffers across pushes."""
    spec = spec if spec is not None else P()
    return _reduce_fn(mesh, axis, op, spec)(x)

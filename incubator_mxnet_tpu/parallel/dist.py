"""Multi-host runtime initialization + the membership-aware helpers.

Reference counterpart: ``tools/launch.py`` + dmlc tracker, which spawned the
ps-lite scheduler/server/worker processes and wired them with ``DMLC_ROLE`` /
``DMLC_PS_ROOT_URI`` / ``DMLC_NUM_WORKER`` env vars (SURVEY §2.5). In the
multi-controller JAX model every host runs the same program;
``jax.distributed.initialize`` plays the scheduler's role (rendezvous at the
coordinator address), after which ``jax.devices()`` spans the whole pod and
every mesh built from it is global. There are no server processes — gradient
exchange is XLA collectives inside the compiled step.

Rebuilt for the elastic control plane (:mod:`.elastic`): initialization now
*banks membership* — after the rendezvous and the first collective-ledger
crosscheck, the heartbeat lease daemon starts (``MXTPU_ELASTIC=1``), so a
host that dies later is a detected loss with a flight bundle, not a pod
wedged inside a collective. Three helpers became load-bearing across the
runtime:

- :func:`is_primary` — THE host-0 election every persistent side effect
  consults (checkpoint manifest commit, shared telemetry paths, artifact
  uploads): collectives must not diverge across hosts, filesystem effects
  must (the MX902 invariant).
- :func:`world` — ``(process_index, process_count)`` without initializing
  a backend, the pair the checkpoint manifest protocol and the data-shard
  view key on.
- :func:`process_namespace` — the per-process token (``"p<idx>"``) that
  namespaces telemetry JSONL files and flight-bundle directories, so every
  host keeps its own forensics with zero shared-file races.

Env-var compatibility: if the dmlc-style vars are present they are mapped
onto the JAX rendezvous so reference launch scripts keep working:

- ``DMLC_PS_ROOT_URI:DMLC_PS_ROOT_PORT`` → coordinator_address
- ``DMLC_NUM_WORKER``                   → num_processes
- ``DMLC_WORKER_ID``                    → process_id
"""
from __future__ import annotations

import os
from typing import Optional, Tuple

import jax

from . import elastic

_INITIALIZED = [False]


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None,
               local_device_ids=None) -> None:
    """Rendezvous this process into the global runtime, crosscheck the
    collective-schedule ledger, and bank elastic membership. No-op when
    single-process (the common single-host case) or already initialized."""
    if _INITIALIZED[0]:
        return
    if coordinator_address is None:
        uri = os.environ.get("DMLC_PS_ROOT_URI")
        port = os.environ.get("DMLC_PS_ROOT_PORT", "9000")
        if uri:
            coordinator_address = f"{uri}:{port}"
    if num_processes is None and "DMLC_NUM_WORKER" in os.environ:
        num_processes = int(os.environ["DMLC_NUM_WORKER"])
    if process_id is None and "DMLC_WORKER_ID" in os.environ:
        process_id = int(os.environ["DMLC_WORKER_ID"])
    if coordinator_address is None and num_processes in (None, 1):
        _INITIALIZED[0] = True  # single-process: nothing to do
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=local_device_ids)
    _INITIALIZED[0] = True
    # first collective-ledger crosscheck the moment the coordination
    # service exists: validates every process reached the same rendezvous
    # (and, on restarts, that restored fingerprint tables agree) before
    # the first real collective can wedge the pod. One env read when the
    # ledger is off.
    from ..telemetry import collective_ledger
    collective_ledger.crosscheck("dist.initialize")
    # membership becomes explicit the moment the pod exists: every
    # process banks a heartbeat lease, and a host that dies from here on
    # is a detected loss (flight bundle + HostLossError), never a silent
    # collective hang. One env read when elastic is off.
    elastic.start()


def finalize() -> None:
    elastic.stop()
    if _INITIALIZED[0]:
        try:
            jax.distributed.shutdown()
        except Exception:
            pass
        _INITIALIZED[0] = False


def process_count() -> int:
    return jax.process_count()


def process_index() -> int:
    return jax.process_index()


def world() -> Tuple[int, int]:
    """``(process_index, process_count)`` from the coordination-service
    state — readable before/without a backend (``(0, 1)`` outside a
    multi-host run), with the dmlc launcher vars as the pre-rendezvous
    fallback so the checkpoint/telemetry layers see a consistent answer
    at import time. The pair the manifest commit protocol, the data
    shard view, and the telemetry namespacing key on."""
    try:
        from jax._src.distributed import global_state
        if getattr(global_state, "client", None) is not None:
            return (int(global_state.process_id or 0),
                    int(global_state.num_processes or 1))
    except Exception:  # noqa: BLE001 — jax version drift → env fallback
        pass
    try:
        idx = int(os.environ.get("DMLC_WORKER_ID", "0") or 0)
        n = int(os.environ.get("DMLC_NUM_WORKER", "1") or 1)
    except ValueError:
        return 0, 1
    return idx, max(n, 1)


def is_primary() -> bool:
    """True on the elected writer host (process 0) — THE election every
    persistent side effect (checkpoint saves, telemetry sinks, artifact
    uploads) must consult in a multi-host run (the MX902 invariant:
    collectives must not diverge across hosts, filesystem effects must).

    Reads the coordination-service state directly so it never initializes
    a backend from a telemetry code path; falls back to the dmlc-style
    ``DMLC_WORKER_ID`` before rendezvous so launch scripts see a
    consistent answer at import time. Single-process runs are always
    primary."""
    return world()[0] == 0


def process_namespace() -> str:
    """The per-process namespacing token for persistent telemetry paths:
    ``""`` single-process (every existing single-host path is untouched),
    ``"p<index>"`` in a multi-host run. ``telemetry.flight`` appends it
    to the bundle directory and ``telemetry.export.JsonlSink`` folds it
    into non-primary stream names, so N hosts write N disjoint files —
    per-host forensics with zero shared-file races, and the primary's
    paths stay exactly where a single-host operator expects them."""
    idx, n = world()
    return f"p{idx}" if n > 1 else ""

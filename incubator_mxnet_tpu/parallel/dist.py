"""Multi-host runtime initialization.

Reference counterpart: ``tools/launch.py`` + dmlc tracker, which spawned the
ps-lite scheduler/server/worker processes and wired them with ``DMLC_ROLE`` /
``DMLC_PS_ROOT_URI`` / ``DMLC_NUM_WORKER`` env vars (SURVEY §2.5). In the
multi-controller JAX model every host runs the same program;
``jax.distributed.initialize`` plays the scheduler's role (rendezvous at the
coordinator address), after which ``jax.devices()`` spans the whole pod and
every mesh built from it is global. There are no server processes — gradient
exchange is XLA collectives inside the compiled step.

Env-var compatibility: if the dmlc-style vars are present they are mapped
onto the JAX rendezvous so reference launch scripts keep working:

- ``DMLC_PS_ROOT_URI:DMLC_PS_ROOT_PORT`` → coordinator_address
- ``DMLC_NUM_WORKER``                   → num_processes
- ``DMLC_WORKER_ID``                    → process_id
"""
from __future__ import annotations

import os
from typing import Optional

import jax

_INITIALIZED = [False]


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None,
               local_device_ids=None) -> None:
    """Rendezvous this process into the global runtime. No-op when
    single-process (the common single-host case) or already initialized."""
    if _INITIALIZED[0]:
        return
    if coordinator_address is None:
        uri = os.environ.get("DMLC_PS_ROOT_URI")
        port = os.environ.get("DMLC_PS_ROOT_PORT", "9000")
        if uri:
            coordinator_address = f"{uri}:{port}"
    if num_processes is None and "DMLC_NUM_WORKER" in os.environ:
        num_processes = int(os.environ["DMLC_NUM_WORKER"])
    if process_id is None and "DMLC_WORKER_ID" in os.environ:
        process_id = int(os.environ["DMLC_WORKER_ID"])
    if coordinator_address is None and num_processes in (None, 1):
        _INITIALIZED[0] = True  # single-process: nothing to do
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=local_device_ids)
    _INITIALIZED[0] = True
    # first collective-ledger crosscheck the moment the coordination
    # service exists: validates every process reached the same rendezvous
    # (and, on restarts, that restored fingerprint tables agree) before
    # the first real collective can wedge the pod. One env read when the
    # ledger is off.
    from ..telemetry import collective_ledger
    collective_ledger.crosscheck("dist.initialize")


def finalize() -> None:
    if _INITIALIZED[0]:
        try:
            jax.distributed.shutdown()
        except Exception:
            pass
        _INITIALIZED[0] = False


def process_count() -> int:
    return jax.process_count()


def process_index() -> int:
    return jax.process_index()


def is_primary() -> bool:
    """True on the elected writer host (process 0) — THE election every
    persistent side effect (checkpoint saves, telemetry sinks, artifact
    uploads) must consult in a multi-host run (the MX902 invariant:
    collectives must not diverge across hosts, filesystem effects must).

    Reads the coordination-service state directly so it never initializes
    a backend from a telemetry code path; falls back to the dmlc-style
    ``DMLC_WORKER_ID`` before rendezvous so launch scripts see a
    consistent answer at import time. Single-process runs are always
    primary."""
    try:
        from jax._src.distributed import global_state
        if getattr(global_state, "client", None) is not None:
            return int(global_state.process_id or 0) == 0
    except Exception:  # noqa: BLE001 — jax version drift → env fallback
        pass
    return os.environ.get("DMLC_WORKER_ID", "0") in ("", "0")

"""Expert parallelism over the ``ep`` mesh axis — switch-style MoE dispatch.

Reference counterpart: none (the reference predates MoE; SURVEY §2.5 lists
``ep`` as a parity-plus extension). TPU-native design: top-1 (switch)
routing with a fixed per-expert capacity so every shape is static; token
exchange between expert shards is ONE ``lax.all_to_all`` over ``ep`` each
way (the canonical MoE dispatch collective, riding ICI), expert FFNs run as
a batched einsum over the local expert shard.

Tokens beyond an expert's capacity are dropped (standard switch-transformer
semantics) — their output contribution is zero, so the surrounding residual
connection passes them through unchanged.
"""
from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from .collectives import shard_map

P = PartitionSpec

__all__ = ["moe_dispatch", "moe_ffn", "moe_ffn_sharded", "MoEFFN"]


def moe_dispatch(x, gate_logits, n_experts: int, capacity: int):
    """Route each token to its top-1 expert within a fixed capacity.

    x (T, C); gate_logits (T, E). Returns ``(dispatched (E, cap, C),
    combine (T,), eidx (T,), pos (T,), keep (T,))`` where ``combine`` is the
    router probability of the chosen expert, and (eidx, pos, keep) place
    each kept token in the dispatch buffer.
    """
    T, C = x.shape
    probs = jax.nn.softmax(gate_logits, axis=-1)
    eidx = jnp.argmax(probs, axis=-1)                     # (T,)
    combine = jnp.take_along_axis(probs, eidx[:, None], 1)[:, 0]
    onehot = jax.nn.one_hot(eidx, n_experts, dtype=jnp.int32)   # (T, E)
    pos = (jnp.cumsum(onehot, axis=0) - 1)                # rank within expert
    pos = jnp.take_along_axis(pos, eidx[:, None], 1)[:, 0]
    keep = pos < capacity
    dispatched = jnp.zeros((n_experts, capacity, C), x.dtype)
    safe_pos = jnp.where(keep, pos, capacity - 1)
    contrib = jnp.where(keep[:, None], x, 0.0)
    dispatched = dispatched.at[eidx, safe_pos].add(contrib)
    return dispatched, combine, eidx, pos, keep


def moe_ffn(params, x, gate_logits, capacity: int, axis: str = "ep"):
    """Expert-parallel switch FFN; call INSIDE shard_map with ``axis`` bound.

    ``params``: dict with ``w1 (E_local, H, C)``, ``b1 (E_local, H)``,
    ``w2 (E_local, C, H)``, ``b2 (E_local, C)`` — the LOCAL expert shard.
    ``x`` (T_local, C) local tokens; ``gate_logits`` (T_local, E_global).
    Returns (T_local, C).
    """
    ep = lax.psum(1, axis)
    e_local = params["w1"].shape[0]
    E = ep * e_local
    T, C = x.shape
    dispatched, combine, eidx, pos, keep = moe_dispatch(
        x, gate_logits, E, capacity)
    # (E, cap, C) = (ep, e_local, cap, C): exchange the ep dim so each shard
    # receives, from every peer, the tokens bound for ITS experts.
    d = dispatched.reshape(ep, e_local, capacity, C)
    d = lax.all_to_all(d, axis, split_axis=0, concat_axis=0, tiled=False)
    # d: (ep_src, e_local, cap, C) — run local experts on all sources at once
    h = jnp.einsum("sekc,ehc->sekh", d, params["w1"],
                   preferred_element_type=jnp.float32)
    h = jax.nn.relu(h + params["b1"][None, :, None, :])
    y = jnp.einsum("sekh,ech->sekc", h.astype(d.dtype), params["w2"],
                   preferred_element_type=jnp.float32).astype(d.dtype)
    y = y + params["b2"][None, :, None, :]
    # route results back to their source shards
    y = lax.all_to_all(y, axis, split_axis=0, concat_axis=0, tiled=False)
    y = y.reshape(E, capacity, C)
    out = y[eidx, jnp.where(keep, pos, 0)]                # (T, C)
    out = jnp.where(keep[:, None], out, 0.0)
    return out * combine[:, None].astype(out.dtype)


def moe_ffn_sharded(mesh: Mesh, params, x, gate_logits, capacity: int,
                    axis: str = "ep"):
    """Host-level entry: ``params`` leaves carry a global leading expert
    axis sharded over ``axis``; tokens shard over ``axis`` too (each expert
    shard is also a token shard — the standard MoE data layout)."""
    pspec = {k: P(axis) for k in params}
    xspec = P(axis)
    fn = shard_map(
        partial(moe_ffn, capacity=capacity, axis=axis),
        mesh=mesh, in_specs=(pspec, xspec, xspec), out_specs=xspec)
    params_s = {k: jax.device_put(v, NamedSharding(mesh, P(axis)))
                for k, v in params.items()}
    xs = jax.device_put(x, NamedSharding(mesh, xspec))
    gs = jax.device_put(gate_logits, NamedSharding(mesh, xspec))
    return jax.jit(fn)(params_s, xs, gs)


class MoEFFN:
    """Gluon-facing switch-FFN layer (built lazily to avoid importing gluon
    at package import)."""

    def __new__(cls, *args, **kwargs):
        from .moe_block import MoEFFNBlock
        return MoEFFNBlock(*args, **kwargs)

"""Ring attention — sequence/context parallelism over the ``sp`` mesh axis.

The reference has NO sequence parallelism (SURVEY §5.7): its longest-context
story is the fused interleaved-MHA kernels in
``src/operator/contrib/transformer.cc`` with the O(L²) score matrix
materialized. This module is the capability-parity-plus counterpart: the
sequence dim is sharded over ``sp``, K/V blocks rotate around the ring via
``lax.ppermute`` (one ICI hop per step), and each hop's block attention runs
the **Pallas flash kernel** (``ops/pallas/flash_attention._fwd``) — the hop
results carry their log-sum-exp and fold into a running softmax merge, so no
device ever holds the full L×L matrix and context length scales linearly
with the ring size.

Shapes follow the contrib-op convention [batch, heads, seq, head_dim].
Key-padding masks (B, L) ride the ring with their K/V block.
"""
from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec
from .collectives import shard_map

P = PartitionSpec

__all__ = ["ring_attention", "ring_attention_sharded"]

_NEG_INF = -1e30


def _hop_flash_ok(q, k) -> bool:
    """Static gate: can this hop's block attention run the Pallas kernel?"""
    import os
    if os.environ.get("MXTPU_RING_IMPL") == "xla":
        return False
    B, H, Lq, D = q.shape
    Lk = k.shape[2]
    if D % 8 or D > 256:
        return False
    from ..ops.pallas.flash_attention import _bq, _bk
    return Lq % _bq(Lq) == 0 and Lk % _bk(Lk) == 0


def _hop_attn(q, k, v, key_mask, causal_mode, q_off, k_off, scale):
    """One K/V block's attention: returns (o_norm fp32, lse fp32).

    ``causal_mode``: 0 = full block, 1 = causal-diagonal block (same-rank
    positions), 2 = fully masked. The Pallas kernel computes modes 0/1; the
    XLA einsum fallback covers unsupported shapes / CPU interpret.
    """
    B, H, Lq, D = q.shape
    Lk = k.shape[2]
    if _hop_flash_ok(q, k):
        from ..ops.pallas.flash_attention import flash_block

        def full_block(q, k, v, key_mask):
            return flash_block(q, k, v, key_mask, False, scale)

        def diag_block(q, k, v, key_mask):
            return flash_block(q, k, v, key_mask, True, scale)

        def masked_block(q, k, v, key_mask):
            return (jnp.zeros((B, H, Lq, D), q.dtype),
                    jnp.full((B, H, Lq), _NEG_INF, jnp.float32))

        o, lse = lax.switch(causal_mode, (full_block, diag_block,
                                          masked_block), q, k, v, key_mask)
        # fully-masked ROWS inside a live block (all-zero key mask) produce
        # o=0, lse = m0+log(eps) ≈ huge negative — already correct for merge
        return o.astype(jnp.float32), lse
    # --- XLA fallback with explicit positions ---
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if key_mask is not None:
        s = jnp.where(key_mask[:, None, None, :].astype(bool), s, _NEG_INF)
    qpos = q_off + jnp.arange(Lq)[:, None]
    kpos = k_off + jnp.arange(Lk)[None, :]
    causal_keep = jnp.where(causal_mode >= 1, qpos >= kpos, True)
    keep = causal_keep & (causal_mode < 2)
    s = jnp.where(keep, s, _NEG_INF)
    m = jnp.max(s, axis=-1)
    # dead entries stay at exactly 0 weight even in fully-masked rows
    # (where m == _NEG_INF and exp(s - m) would otherwise be 1)
    p = jnp.where(s > _NEG_INF * 0.5, jnp.exp(s - m[..., None]), 0.0)
    l = jnp.sum(p, axis=-1)
    o = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    o = o / jnp.maximum(l, 1e-30)[..., None]
    lse = jnp.where(l > 0, m + jnp.log(jnp.maximum(l, 1e-30)), _NEG_INF)
    return o, lse


def _merge(o, lse, o_i, lse_i):
    """Fold a hop's normalized partial into the running result (the
    flash-attention two-pass merge rule over log-sum-exps)."""
    lse_new = jnp.logaddexp(lse, lse_i)
    w_old = jnp.exp(jnp.minimum(lse - lse_new, 0.0))
    w_new = jnp.exp(jnp.minimum(lse_i - lse_new, 0.0))
    return o * w_old[..., None] + o_i * w_new[..., None], lse_new


def ring_attention(q, k, v, key_mask=None, axis: str = "sp",
                   causal: bool = False, scale: Optional[float] = None):
    """Attention over sequence shards; call inside shard_map with ``axis``
    bound. q/k/v: [B, H, L_local, D] local shards of the L dimension;
    ``key_mask``: optional (B, L_local) validity shard riding the ring."""
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    n = lax.psum(1, axis)
    idx = lax.axis_index(axis)
    lq = q.shape[2]
    b, h = q.shape[0], q.shape[1]

    o0 = jnp.zeros((b, h, lq, q.shape[3]), jnp.float32)
    lse0 = jnp.full((b, h, lq), _NEG_INF, jnp.float32)
    q_off = idx * lq
    perm = [(j, (j + 1) % n) for j in range(n)]
    mask0 = key_mask if key_mask is not None \
        else jnp.ones((b, k.shape[2]), jnp.int32)

    def hop(i, carry):
        o, lse, k_cur, v_cur, m_cur = carry
        src = (idx - i) % n          # whose block we currently hold
        if causal:
            # equal shard sizes ⇒ whole blocks compare by rank:
            # src < idx → all keys precede queries (full);
            # src == idx → diagonal (causal); src > idx → fully masked
            mode = jnp.where(src == idx, 1, jnp.where(src < idx, 0, 2))
        else:
            mode = jnp.zeros((), jnp.int32)
        o_i, lse_i = _hop_attn(q, k_cur, v_cur, m_cur, mode,
                               q_off, src * lq, scale)
        o, lse = _merge(o, lse, o_i, lse_i)
        k_nxt = lax.ppermute(k_cur, axis, perm)
        v_nxt = lax.ppermute(v_cur, axis, perm)
        m_nxt = lax.ppermute(m_cur, axis, perm)
        return o, lse, k_nxt, v_nxt, m_nxt

    o, lse, k_last, v_last, m_last = lax.fori_loop(
        0, n - 1, hop, (o0, lse0, k, v, mask0))
    o, lse, *_ = hop(n - 1, (o, lse, k_last, v_last, m_last))
    # rows with no live key anywhere (lse at the -1e30 floor) → zeros
    o = jnp.where((lse > _NEG_INF * 0.5)[..., None], o, 0.0)
    return o.astype(q.dtype)


def ring_attention_sharded(mesh: Mesh, q, k, v, key_mask=None,
                           causal: bool = False,
                           scale: Optional[float] = None, axis: str = "sp"):
    """Host-level entry: q/k/v global [B,H,L,D]; shards L over ``axis``,
    batch over ``dp`` when that axis exists, heads over ``tp`` so a
    tensor-parallel attention stays local in its head shard."""
    bspec = "dp" if mesh.shape.get("dp", 1) > 1 else None
    hspec = "tp" if mesh.shape.get("tp", 1) > 1 else None
    spec = P(bspec, hspec, axis, None)
    mspec = P(bspec, axis)
    if key_mask is None:
        fn = shard_map(
            partial(ring_attention, key_mask=None, axis=axis, causal=causal,
                    scale=scale),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
        args = tuple(jax.device_put(x, NamedSharding(mesh, spec))
                     for x in (q, k, v))
        return jax.jit(fn)(*args)
    fn = shard_map(
        partial(ring_attention, axis=axis, causal=causal, scale=scale),
        mesh=mesh, in_specs=(spec, spec, spec, mspec), out_specs=spec)
    args = tuple(jax.device_put(x, NamedSharding(mesh, spec))
                 for x in (q, k, v))
    km = jax.device_put(jnp.asarray(key_mask), NamedSharding(mesh, mspec))
    return jax.jit(fn)(*args, km)

"""Ring attention — sequence/context parallelism over the ``sp`` mesh axis.

The reference has NO sequence parallelism (SURVEY §5.7): its longest-context
story is the fused interleaved-MHA kernels in
``src/operator/contrib/transformer.cc`` with the O(L²) score matrix
materialized. This module is the capability-parity-plus counterpart: the
sequence dim is sharded over ``sp``, K/V blocks rotate around the ring via
``lax.ppermute`` (one ICI hop per step), and each hop folds into a running
flash-style online softmax — so no device ever holds the full L×L matrix and
context length scales linearly with the ring size.

Shapes follow the contrib-op convention [batch, heads, seq, head_dim].
"""
from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec
from .collectives import shard_map

P = PartitionSpec

__all__ = ["ring_attention", "ring_attention_sharded"]

_NEG_INF = -1e30


def _block_attn(q, k, v, o, m, l, q_off, k_off, scale, causal):
    """One ring hop: fold local K/V block into the online-softmax state."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        lq, lk = q.shape[2], k.shape[2]
        qpos = q_off + jnp.arange(lq)[:, None]
        kpos = k_off + jnp.arange(lk)[None, :]
        s = jnp.where(qpos >= kpos, s, _NEG_INF)
    m_new = jnp.maximum(m, s.max(axis=-1))
    # guard fully-masked rows (exp(-inf - -inf)): keep them at zero weight
    alpha = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new[..., None])
    l_new = l * alpha + p.sum(axis=-1)
    o_new = o * alpha[..., None] + jnp.einsum(
        "bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    return o_new, m_new, l_new


def ring_attention(q, k, v, axis: str = "sp", causal: bool = False,
                   scale: Optional[float] = None):
    """Attention over sequence shards; call inside shard_map with ``axis``
    bound. q/k/v: [B, H, L_local, D] local shards of the L dimension."""
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    n = lax.psum(1, axis)
    idx = lax.axis_index(axis)
    lq, lk = q.shape[2], k.shape[2]
    b, h = q.shape[0], q.shape[1]

    o0 = jnp.zeros((b, h, lq, q.shape[3]), jnp.float32)
    m0 = jnp.full((b, h, lq), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, lq), jnp.float32)
    q_off = idx * lq
    perm = [(j, (j + 1) % n) for j in range(n)]

    def body(i, carry):
        o, m, l, k_cur, v_cur = carry
        src = (idx - i) % n          # whose block we currently hold
        o, m, l = _block_attn(q, k_cur, v_cur, o, m, l,
                              q_off, src * lk, scale, causal)
        k_nxt = lax.ppermute(k_cur, axis, perm)
        v_nxt = lax.ppermute(v_cur, axis, perm)
        return o, m, l, k_nxt, v_nxt

    # n-1 hops with rotation, then fold the final held block without the
    # wasted last rotation.
    o, m, l, k_last, v_last = lax.fori_loop(0, n - 1, body, (o0, m0, l0, k, v))
    o, m, l = _block_attn(q, k_last, v_last, o, m, l,
                          q_off, ((idx - (n - 1)) % n) * lk, scale, causal)
    l = jnp.where(l == 0.0, 1.0, l)
    return (o / l[..., None]).astype(q.dtype)


def ring_attention_sharded(mesh: Mesh, q, k, v, causal: bool = False,
                           scale: Optional[float] = None, axis: str = "sp"):
    """Host-level entry: q/k/v global [B,H,L,D]; shards L over ``axis``,
    batch over ``dp`` when that axis exists."""
    bspec = "dp" if mesh.shape.get("dp", 1) > 1 else None
    spec = P(bspec, None, axis, None)
    fn = shard_map(
        partial(ring_attention, axis=axis, causal=causal, scale=scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    args = tuple(jax.device_put(x, NamedSharding(mesh, spec)) for x in (q, k, v))
    return jax.jit(fn)(*args)

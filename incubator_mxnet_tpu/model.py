"""Checkpoint helpers + training-loop plumbing shared by Module/callbacks.

Reference parity: ``python/mxnet/model.py`` — ``save_checkpoint``/
``load_checkpoint`` (prefix-epoch .params files, SURVEY §5.4) and the
``BatchEndParam`` record passed to batch callbacks.
"""
from __future__ import annotations

import json
from collections import namedtuple
from typing import Dict, Optional, Tuple

from . import ndarray as nd

__all__ = ["BatchEndParam", "save_checkpoint", "load_checkpoint", "load_params"]

BatchEndParam = namedtuple("BatchEndParam",
                           ["epoch", "nbatch", "eval_metric", "locals"])


def save_checkpoint(prefix: str, epoch: int, symbol=None,
                    arg_params: Optional[Dict] = None,
                    aux_params: Optional[Dict] = None,
                    remove_amp_cast: bool = True) -> None:
    """``prefix-symbol.json`` + ``prefix-%04d.params`` (reference layout:
    arg/aux namespaced with ``arg:``/``aux:`` key prefixes)."""
    if symbol is not None:
        sym_json = symbol.tojson() if hasattr(symbol, "tojson") else json.dumps(
            {"symbol": str(symbol)})
        with open(f"{prefix}-symbol.json", "w") as f:
            f.write(sym_json)
    save_dict = {f"arg:{k}": v for k, v in (arg_params or {}).items()}
    save_dict.update({f"aux:{k}": v for k, v in (aux_params or {}).items()})
    nd.save(f"{prefix}-{epoch:04d}.params", save_dict)


def load_params(prefix: str, epoch: int) -> Tuple[Dict, Dict]:
    loaded = nd.load(f"{prefix}-{epoch:04d}.params")
    arg_params, aux_params = {}, {}
    for k, v in loaded.items():
        tp, _, name = k.partition(":")
        if tp == "arg":
            arg_params[name] = v
        elif tp == "aux":
            aux_params[name] = v
        else:
            arg_params[k] = v
    return arg_params, aux_params


def load_checkpoint(prefix: str, epoch: int):
    """Returns (symbol, arg_params, aux_params); symbol is None when no
    symbol file exists (gluon-era checkpoints)."""
    symbol = None
    try:
        from . import symbol as sym_mod
        symbol = sym_mod.load(f"{prefix}-symbol.json")
    except Exception:
        symbol = None
    arg_params, aux_params = load_params(prefix, epoch)
    return symbol, arg_params, aux_params

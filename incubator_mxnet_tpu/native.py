"""ctypes bindings for the C++ runtime shim (native/mxtpu_native.cc).

Reference parity: the ctypes half of the C ABI boundary
(``python/mxnet/base.py`` ``_LIB`` loading libmxnet.so — SURVEY §2.7). The
shared library is built on demand from ``native/`` with the system g++; all
callers degrade gracefully to the pure-Python paths when a toolchain is
unavailable (``native.available()``).

Surfaces:
- :class:`NativeRecordReader` / :class:`NativeRecordWriter` / index_build —
  the C++ recordio parser (src/io/ parity).
- :class:`ShmSegment` — named POSIX shared memory
  (CPUSharedStorageManager parity) for DataLoader worker transfer.
- :class:`NativeEngine` — host-side dependency engine (ThreadedEngine
  parity): push(fn, read_vars, write_vars), wait_all.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Callable, List, Optional, Sequence

from .base import MXNetError
from .lockcheck import make_lock

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_NATIVE_DIR = os.path.join(_REPO_ROOT, "native")
_SO_PATH = os.path.join(_NATIVE_DIR, "libmxtpu_native.so")

_LIB: Optional[ctypes.CDLL] = None
_LOAD_LOCK = make_lock("native._LOAD_LOCK")
_LOAD_FAILED = False

_TASK_FN = ctypes.CFUNCTYPE(None, ctypes.c_void_p)


def _build() -> bool:
    try:
        subprocess.run(["make", "-C", _NATIVE_DIR],
                       check=True, capture_output=True, timeout=120)
        return os.path.exists(_SO_PATH)
    except Exception:
        return False


def _lib() -> ctypes.CDLL:
    global _LIB, _LOAD_FAILED
    if _LIB is not None:
        return _LIB
    with _LOAD_LOCK:
        if _LIB is not None:
            return _LIB
        if _LOAD_FAILED:
            raise MXNetError("native library unavailable (build failed)")
        if not os.path.exists(_SO_PATH) and not _build():
            _LOAD_FAILED = True
            raise MXNetError(
                "cannot build native/libmxtpu_native.so (no toolchain?)")
        lib = ctypes.CDLL(_SO_PATH)
        lib.MXTPUGetLastError.restype = ctypes.c_char_p
        lib.MXTPURecordIOWriterCreate.restype = ctypes.c_void_p
        lib.MXTPURecordIOWriterCreate.argtypes = [ctypes.c_char_p]
        lib.MXTPURecordIOWriterWrite.restype = ctypes.c_int
        lib.MXTPURecordIOWriterWrite.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_uint64)]
        lib.MXTPURecordIOWriterFree.argtypes = [ctypes.c_void_p]
        lib.MXTPURecordIOReaderCreate.restype = ctypes.c_void_p
        lib.MXTPURecordIOReaderCreate.argtypes = [ctypes.c_char_p]
        lib.MXTPURecordIOReaderSeek.restype = ctypes.c_int
        lib.MXTPURecordIOReaderSeek.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.MXTPURecordIOReaderNext.restype = ctypes.c_int64
        lib.MXTPURecordIOReaderNext.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_int)]
        lib.MXTPURecordIOReaderTell.restype = ctypes.c_uint64
        lib.MXTPURecordIOReaderTell.argtypes = [ctypes.c_void_p]
        lib.MXTPURecordIOWriterTell.restype = ctypes.c_uint64
        lib.MXTPURecordIOWriterTell.argtypes = [ctypes.c_void_p]
        lib.MXTPURecordIOReaderFree.argtypes = [ctypes.c_void_p]
        lib.MXTPURecordIOIndexBuild.restype = ctypes.c_int64
        lib.MXTPURecordIOIndexBuild.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_uint64), ctypes.c_int64]
        lib.MXTPUIm2RecCreate.restype = ctypes.c_void_p
        lib.MXTPUIm2RecCreate.argtypes = [ctypes.c_char_p]
        lib.MXTPUIm2RecWrite.restype = ctypes.c_int
        lib.MXTPUIm2RecWrite.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.POINTER(ctypes.c_float),
            ctypes.c_uint32, ctypes.c_int, ctypes.c_uint64, ctypes.c_uint64,
            ctypes.c_char_p, ctypes.c_uint64]
        lib.MXTPUIm2RecClose.restype = ctypes.c_int
        lib.MXTPUIm2RecClose.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.MXTPUShmCreate.restype = ctypes.c_void_p
        lib.MXTPUShmCreate.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
        lib.MXTPUShmAttach.restype = ctypes.c_void_p
        lib.MXTPUShmAttach.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
        lib.MXTPUShmPtr.restype = ctypes.c_void_p
        lib.MXTPUShmPtr.argtypes = [ctypes.c_void_p]
        lib.MXTPUShmSize.restype = ctypes.c_uint64
        lib.MXTPUShmSize.argtypes = [ctypes.c_void_p]
        lib.MXTPUShmFree.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.MXTPUEngineCreate.restype = ctypes.c_void_p
        lib.MXTPUEngineCreate.argtypes = [ctypes.c_int]
        lib.MXTPUEngineNewVar.restype = ctypes.c_int64
        lib.MXTPUEngineNewVar.argtypes = [ctypes.c_void_p]
        lib.MXTPUEnginePush.argtypes = [
            ctypes.c_void_p, _TASK_FN, ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int,
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int]
        lib.MXTPUEngineWaitAll.argtypes = [ctypes.c_void_p]
        lib.MXTPUEngineFree.argtypes = [ctypes.c_void_p]
        lib.MXTPUParamsWriterCreate.restype = ctypes.c_void_p
        lib.MXTPUParamsWriterCreate.argtypes = [ctypes.c_char_p]
        lib.MXTPUParamsWriterAdd.restype = ctypes.c_int
        lib.MXTPUParamsWriterAdd.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int32, ctypes.c_uint32,
            ctypes.POINTER(ctypes.c_int64), ctypes.c_void_p, ctypes.c_uint64]
        lib.MXTPUParamsWriterFinish.restype = ctypes.c_int
        lib.MXTPUParamsWriterFinish.argtypes = [ctypes.c_void_p]
        lib.MXTPUParamsWriterFree.argtypes = [ctypes.c_void_p]
        lib.MXTPUParamsReaderCreate.restype = ctypes.c_void_p
        lib.MXTPUParamsReaderCreate.argtypes = [ctypes.c_char_p]
        lib.MXTPUParamsReaderCount.restype = ctypes.c_int64
        lib.MXTPUParamsReaderCount.argtypes = [ctypes.c_void_p]
        lib.MXTPUParamsReaderGet.restype = ctypes.c_int
        lib.MXTPUParamsReaderGet.argtypes = [
            ctypes.c_void_p, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_uint32),
            ctypes.POINTER(ctypes.POINTER(ctypes.c_int64)),
            ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_uint64)]
        lib.MXTPUParamsReaderFree.argtypes = [ctypes.c_void_p]
        _LIB = lib
        return _LIB


def available() -> bool:
    try:
        _lib()
        return True
    except MXNetError:
        return False


def last_error() -> str:
    return _lib().MXTPUGetLastError().decode()


# ---------------------------------------------------------------------------
# RecordIO
# ---------------------------------------------------------------------------

class NativeRecordWriter:
    def __init__(self, path: str):
        self._h = _lib().MXTPURecordIOWriterCreate(path.encode())
        if not self._h:
            raise MXNetError(last_error())

    def write(self, buf: bytes) -> int:
        pos = ctypes.c_uint64()
        if _lib().MXTPURecordIOWriterWrite(self._h, buf, len(buf),
                                           ctypes.byref(pos)) != 0:
            raise MXNetError(last_error())
        return pos.value

    def tell(self) -> int:
        return _lib().MXTPURecordIOWriterTell(self._h)

    def close(self):
        if self._h:
            _lib().MXTPURecordIOWriterFree(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class NativeIm2RecWriter:
    """C++ im2rec packer hot loop (reference: tools/im2rec.cc): per record,
    IRHeader pack + dmlc framing + index entry happen in one native call;
    close() writes the ``.idx`` sidecar. Byte-identical to the Python
    ``recordio.pack`` + ``MXIndexedRecordIO`` path."""

    def __init__(self, rec_path: str, idx_path: str):
        self._idx_path = idx_path
        self._h = _lib().MXTPUIm2RecCreate(rec_path.encode())
        if not self._h:
            raise MXNetError(last_error())

    def write(self, key: int, label, id_: int, payload: bytes,
              id2: int = 0) -> None:
        import numpy as _onp
        multi = isinstance(label, (list, tuple, _onp.ndarray))
        labels = [float(x) for x in _onp.asarray(label).reshape(-1)] \
            if multi else [label]
        arr = (ctypes.c_float * len(labels))(*[float(x) for x in labels])
        if _lib().MXTPUIm2RecWrite(self._h, key, arr, len(labels),
                                   int(multi), id_, id2,
                                   payload, len(payload)) != 0:
            raise MXNetError(last_error())

    def close(self):
        if self._h:
            rc = _lib().MXTPUIm2RecClose(self._h, self._idx_path.encode())
            self._h = None
            if rc != 0:
                raise MXNetError(last_error())

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class NativeRecordReader:
    def __init__(self, path: str):
        self._h = _lib().MXTPURecordIOReaderCreate(path.encode())
        if not self._h:
            raise MXNetError(last_error())

    def seek(self, pos: int) -> None:
        _lib().MXTPURecordIOReaderSeek(self._h, pos)

    def read(self) -> Optional[bytes]:
        out = ctypes.c_char_p()
        eof = ctypes.c_int()
        n = _lib().MXTPURecordIOReaderNext(self._h, ctypes.byref(out),
                                           ctypes.byref(eof))
        if n < 0:
            raise MXNetError(last_error())
        if eof.value:
            return None
        return ctypes.string_at(out, n)

    def tell(self) -> int:
        return _lib().MXTPURecordIOReaderTell(self._h)

    def close(self):
        if self._h:
            _lib().MXTPURecordIOReaderFree(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def index_build(path: str) -> List[int]:
    """Native two-pass index: count records, then fill the exact-size
    offset array (the C function tolerates a NULL buffer for counting)."""
    lib = _lib()
    n = lib.MXTPURecordIOIndexBuild(path.encode(), None, 0)
    if n < 0:
        raise MXNetError(last_error())
    if n == 0:
        return []
    arr = (ctypes.c_uint64 * n)()
    n2 = lib.MXTPURecordIOIndexBuild(path.encode(), arr, n)
    if n2 < 0:
        raise MXNetError(last_error())
    return list(arr[:n2])


# ---------------------------------------------------------------------------
# Shared memory
# ---------------------------------------------------------------------------

class ShmSegment:
    """Named POSIX shared memory, zero-copy viewable as a numpy buffer."""

    def __init__(self, name: str, size: int, create: bool = True):
        lib = _lib()
        fn = lib.MXTPUShmCreate if create else lib.MXTPUShmAttach
        self._h = fn(name.encode(), size)
        if not self._h:
            raise MXNetError(last_error())
        self.name = name
        self.size = size
        self._create = create

    def as_numpy(self, shape, dtype):
        import numpy as onp

        class _ShmArray(onp.ndarray):
            # ndarray subclass so the view can pin the segment: the mapping
            # must outlive every array built on it.
            pass

        ptr = _lib().MXTPUShmPtr(self._h)
        n = int(onp.prod(shape)) * onp.dtype(dtype).itemsize
        if n > self.size:
            raise MXNetError(f"shm segment too small: {n} > {self.size}")
        buf = (ctypes.c_char * n).from_address(ptr)
        arr = onp.frombuffer(buf, dtype=dtype).reshape(shape).view(_ShmArray)
        arr._segment = self
        return arr

    def __exit__(self, *exc):
        self.close()

    def __enter__(self):
        return self

    def close(self, unlink: Optional[bool] = None):
        if self._h:
            _lib().MXTPUShmFree(self._h, 1 if (unlink if unlink is not None
                                               else self._create) else 0)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


# ---------------------------------------------------------------------------
# Dependency engine
# ---------------------------------------------------------------------------

class NativeEngine:
    """Host-side async executor with read/write var dependencies
    (ThreadedEngine semantics: concurrent readers, exclusive ordered
    writers)."""

    def __init__(self, num_workers: int = 0):
        self._h = _lib().MXTPUEngineCreate(num_workers)
        # ctypes callbacks stay referenced until wait_all(): freeing one from
        # inside its own trampoline would unmap the ffi closure the C worker
        # thread is still returning through.
        self._keepalive: list = []
        self._lock = make_lock("NativeEngine._lock")
        # Async exception propagation (reference:
        # ThreadedEngine::OnCompleteStatic capture → rethrow in WaitToRead,
        # SURVEY §5.2): a task's exception is captured on the worker thread
        # and rethrown at the next wait_all() sync point — never swallowed,
        # never crashing the worker.
        self._errors: list = []

    def new_var(self) -> int:
        return _lib().MXTPUEngineNewVar(self._h)

    def push(self, fn: Callable[[], None],
             read_vars: Sequence[int] = (),
             write_vars: Sequence[int] = ()) -> None:
        def trampoline(_ctx, _fn=fn):
            try:
                _fn()
            except BaseException as e:  # noqa: BLE001 — must cross threads
                with self._lock:
                    self._errors.append(e)

        cfn = _TASK_FN(trampoline)
        with self._lock:
            self._keepalive.append(cfn)
        rv = (ctypes.c_int64 * max(1, len(read_vars)))(*read_vars)
        wv = (ctypes.c_int64 * max(1, len(write_vars)))(*write_vars)
        _lib().MXTPUEnginePush(self._h, cfn, None, rv, len(read_vars),
                               wv, len(write_vars))

    def wait_all(self) -> None:
        _lib().MXTPUEngineWaitAll(self._h)
        # all pushed tasks have returned through their closures; safe to free
        with self._lock:
            self._keepalive.clear()
            errors, self._errors = self._errors, []
        if errors:
            raise errors[0]

    def close(self):
        """Drain, free, and rethrow any captured task exception — close() is
        a sync point like wait_all() (the __del__ path swallows, as Python
        finalizers must)."""
        if self._h:
            _lib().MXTPUEngineWaitAll(self._h)
            _lib().MXTPUEngineFree(self._h)
            self._h = None
            with self._lock:
                self._keepalive.clear()
                errors, self._errors = self._errors, []
            if errors:
                raise errors[0]

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


# ---------------------------------------------------------------------------
# dmlc .params container (NDArray::Save/Load parity)
# ---------------------------------------------------------------------------

def params_save(path: str, arrays, names, dtype_flags) -> None:
    """Write the kMXAPINDArrayListMagic container natively. ``arrays`` are
    C-contiguous numpy arrays, ``dtype_flags`` their mshadow type flags
    (serialization._DTYPE_TO_FLAG)."""
    lib = _lib()
    h = lib.MXTPUParamsWriterCreate(path.encode())
    if not h:
        raise MXNetError(last_error())
    try:
        for i, (a, flag) in enumerate(zip(arrays, dtype_flags)):
            # unnamed list saves carry no names section (names may be empty
            # or shorter than arrays) — NULL name marks "unnamed"
            name = names[i].encode() if i < len(names) else None
            shape = (ctypes.c_int64 * max(1, a.ndim))(*a.shape)
            if lib.MXTPUParamsWriterAdd(
                    h, name, flag, a.ndim, shape,
                    a.ctypes.data_as(ctypes.c_void_p), a.nbytes) != 0:
                raise MXNetError(last_error())
        if lib.MXTPUParamsWriterFinish(h) != 0:
            raise MXNetError(last_error())
    finally:
        lib.MXTPUParamsWriterFree(h)


def params_load(path: str):
    """Read a dmlc .params container natively → (arrays, names, flags).
    Raises MXNetError on any layout the C++ reader doesn't cover (V1/legacy/
    sparse records) — the caller falls back to the Python reader."""
    import numpy as onp
    lib = _lib()
    h = lib.MXTPUParamsReaderCreate(path.encode())
    if not h:
        raise MXNetError(last_error())
    try:
        n = lib.MXTPUParamsReaderCount(h)
        arrays, names, flags = [], [], []
        for i in range(n):
            name = ctypes.c_char_p()
            flag = ctypes.c_int32()
            ndim = ctypes.c_uint32()
            shape_p = ctypes.POINTER(ctypes.c_int64)()
            data_p = ctypes.c_void_p()
            nbytes = ctypes.c_uint64()
            if lib.MXTPUParamsReaderGet(
                    h, i, ctypes.byref(name), ctypes.byref(flag),
                    ctypes.byref(ndim), ctypes.byref(shape_p),
                    ctypes.byref(data_p), ctypes.byref(nbytes)) != 0:
                raise MXNetError(last_error())
            shape = tuple(shape_p[d] for d in range(ndim.value))
            raw = ctypes.string_at(data_p, nbytes.value) if nbytes.value \
                else b""
            arrays.append((shape, raw))
            if name.value is not None:  # NULL ⇒ unnamed list save
                names.append(name.value.decode())
            flags.append(flag.value)
        return arrays, names, flags
    finally:
        lib.MXTPUParamsReaderFree(h)

"""``mx.np`` — the NumPy-compatible array namespace.

Reference parity: ``python/mxnet/numpy/`` (SURVEY §2.7) — the np-on-device
API MXNet 1.6+ ships next to ``mx.nd``. TPU-natively this is nearly free:
jax.numpy IS a NumPy implementation, so every function here wraps the jnp
twin, keeps arrays as autograd-recording :class:`NDArray` handles, and
inherits XLA compilation. Functions not listed fall through via __getattr__
to a generated jnp wrapper, so coverage is the whole jnp surface.
"""
from __future__ import annotations

import sys
from typing import Any

import jax
import jax.numpy as jnp
import numpy as onp

from ..base import MXNetError
from ..context import current_context
from ..ndarray import NDArray
from ..ndarray.op import dispatch_op

__all__ = ["ndarray", "array", "zeros", "ones", "empty", "full", "arange",
           "pi", "e", "inf", "nan", "newaxis", "random"]

_this = sys.modules[__name__]

ndarray = NDArray
newaxis = None
pi = onp.pi
e = onp.e
inf = onp.inf
nan = onp.nan
float32 = onp.float32
float64 = onp.float64
int32 = onp.int32
int64 = onp.int64
uint8 = onp.uint8
bool_ = onp.bool_
from jax.numpy import bfloat16  # noqa: E402,F401
float16 = onp.float16


def array(obj, dtype=None, ctx=None) -> NDArray:
    return NDArray(obj, ctx=ctx or current_context(), dtype=dtype)


def zeros(shape, dtype=None, ctx=None, order="C") -> NDArray:
    return NDArray(jnp.zeros(shape, dtype or jnp.float32), ctx=ctx)


def ones(shape, dtype=None, ctx=None, order="C") -> NDArray:
    return NDArray(jnp.ones(shape, dtype or jnp.float32), ctx=ctx)


empty = zeros


def full(shape, fill_value, dtype=None, ctx=None) -> NDArray:
    return NDArray(jnp.full(shape, fill_value, dtype), ctx=ctx)


def arange(start, stop=None, step=1, dtype=None, ctx=None) -> NDArray:
    return NDArray(jnp.arange(start, stop, step, dtype), ctx=ctx)


def _wrap_jnp(name: str):
    jfn = getattr(jnp, name)
    if not callable(jfn):
        return jfn

    def fn(*args, **kwargs):
        leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
        arr_pos = [i for i, l in enumerate(leaves) if isinstance(l, NDArray)]
        if not arr_pos:
            out = jfn(*args, **kwargs)
            if isinstance(out, jnp.ndarray):
                return NDArray(out)
            return out
        ctx = leaves[arr_pos[0]].context
        arrays = [leaves[i] for i in arr_pos]

        def pure(*vals):
            lv = list(leaves)
            for i, v in zip(arr_pos, vals):
                lv[i] = v
            a, kw = jax.tree_util.tree_unflatten(treedef, lv)
            return jfn(*a, **kw)

        return dispatch_op(pure, arrays, kwargs, ctx, name=f"np.{name}")

    fn.__name__ = name
    fn.__qualname__ = f"np.{name}"
    fn.__doc__ = getattr(jfn, "__doc__", None)
    return fn


_trapezoid = None
_asarray_routed = None


def trapz(y, x=None, dx=1.0, axis=-1):
    """numpy<2 spelling of the trapezoid rule (jnp only has `trapezoid`);
    routed through dispatch_op like every generated wrapper, so autograd
    records it and the context is preserved."""
    global _trapezoid
    if _trapezoid is None:
        _trapezoid = _wrap_jnp("trapezoid")
    f = _trapezoid
    return f(y, x, dx=dx, axis=axis) if x is not None else f(y, dx=dx,
                                                             axis=axis)


def ascontiguousarray(a, dtype=None):
    """Layout is XLA's concern; equivalent to asarray here (dispatch-routed
    so the gradient chain and context survive)."""
    global _asarray_routed
    if _asarray_routed is None:
        _asarray_routed = _wrap_jnp("asarray")
    f = _asarray_routed
    return f(a, dtype=dtype) if dtype is not None else f(a)


def shares_memory(a, b, max_work=None):
    """NDArray/jax operands: True only when both wrap the SAME device buffer
    (jax arrays are immutable, so distinct buffers never alias). Raw numpy
    operands delegate to numpy's own overlap analysis."""
    av = a._data if isinstance(a, NDArray) else a
    bv = b._data if isinstance(b, NDArray) else b
    if isinstance(av, onp.ndarray) and isinstance(bv, onp.ndarray):
        return bool(onp.shares_memory(av, bv))
    return av is bv


def may_share_memory(a, b, max_work=None):
    av = a._data if isinstance(a, NDArray) else a
    bv = b._data if isinstance(b, NDArray) else b
    if isinstance(av, onp.ndarray) and isinstance(bv, onp.ndarray):
        return bool(onp.may_share_memory(av, bv))
    return av is bv


def __getattr__(name: str) -> Any:
    if hasattr(jnp, name):
        wrapped = _wrap_jnp(name)
        setattr(_this, name, wrapped)
        return wrapped
    raise AttributeError(f"module 'mx.np' has no attribute {name!r}")


def __dir__():
    return sorted(set(list(globals()) + dir(jnp)))


class _NPRandom:
    """mx.np.random — stateful-feeling wrapper over the Context RNG."""

    @staticmethod
    def _key():
        from .. import random as random_mod
        return random_mod.next_key(current_context())

    def uniform(self, low=0.0, high=1.0, size=None, dtype=None, ctx=None):
        shape = size if size is not None else ()
        out = jax.random.uniform(self._key(), shape, dtype or jnp.float32,
                                 low, high)
        return NDArray(out, ctx=ctx)

    def normal(self, loc=0.0, scale=1.0, size=None, dtype=None, ctx=None):
        shape = size if size is not None else ()
        out = jax.random.normal(self._key(), shape, dtype or jnp.float32)
        return NDArray(out * scale + loc, ctx=ctx)

    def randint(self, low, high=None, size=None, dtype=None, ctx=None):
        if high is None:
            low, high = 0, low
        shape = size if size is not None else ()
        out = jax.random.randint(self._key(), shape, low, high,
                                 dtype or jnp.int32)
        return NDArray(out, ctx=ctx)

    def choice(self, a, size=None, replace=True, p=None, ctx=None):
        arr = a._data if isinstance(a, NDArray) else jnp.asarray(a)
        shape = size if size is not None else ()
        p_ = p._data if isinstance(p, NDArray) else p
        out = jax.random.choice(self._key(), arr, shape, replace, p_)
        return NDArray(out, ctx=ctx)

    def shuffle(self, x: NDArray) -> None:
        x._set_data(jax.random.permutation(self._key(), x._data))

    def seed(self, s):
        from .. import random as random_mod
        random_mod.seed(int(s))


random = _NPRandom()

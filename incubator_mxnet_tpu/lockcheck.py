"""Runtime lock-order sanitizer — the dynamic twin of ``mx.analysis.
concurrency``'s static MX802 lock graph.

Reference counterpart: none — the reference's ThreadedEngine ordered all
mutation through its dependency engine, so lock discipline was the
engine's problem. Here the production tier (DynamicBatcher, the TCP
server, AsyncKVStore/AsyncPSServer, the telemetry bus, watchdog, chaos
injector) holds ~100 ``threading.Thread``/``Lock`` sites, and a
lock-order inversion between any two of them is a deadlock that no test
fails and no exception reports — the same "silent failure" class the
recompile ledger closes for jit caches (MX706 ↔ ``telemetry.
compile_log``); this module closes it for locks (MX802 ↔ lockcheck).

Mechanics (opt-in; zero overhead when off):

- every lock in the package is created through :func:`make_lock` /
  :func:`make_rlock` with a stable name matching the static analysis'
  lock id (``"DynamicBatcher._lock"``, ``"compile_log._LOCK"``). When
  lockcheck is OFF (the default) these return plain ``threading.Lock``/
  ``RLock`` objects — the production fast path is untouched.
- under ``MXTPU_LOCKCHECK=1`` (or any ``MXTPU_CHAOS`` run — stress runs
  get the sanitizer for free) they return :class:`TrackedLock` /
  :class:`TrackedRLock`: each acquisition records the *edge* from every
  lock the thread already holds to the lock being acquired, into one
  process-wide order graph.
- an acquisition whose reversed edge is already in the graph is a
  **lock-order inversion**: it is recorded (:func:`inversions`),
  published as a ``concurrency.inversion`` telemetry event (severity
  error), counted in ``mxtpu_lockcheck_inversions_total`` — and the
  acquire proceeds with a bounded timeout (``MXTPU_LOCKCHECK_TIMEOUT_S``)
  instead of blocking forever, raising :class:`LockOrderError` on
  expiry, so a real deadlock flags and *fails* rather than hanging the
  process (the seeded two-lock fixture test relies on this bound).
- re-acquiring a non-reentrant :class:`TrackedLock` on the same thread
  is certain self-deadlock: flagged and raised immediately.
- releases longer than ``MXTPU_LOCKCHECK_HOLD_MS`` after acquisition
  publish a ``concurrency.hold`` warning event (lock-hold latency is the
  serving tail's favourite hiding place).

Cross-checking against the static graph lives in
``mx.analysis.concurrency.crosscheck()``: runtime edges the static MX802
pass never derived are its blind spots; static cycle edges observed live
corroborate the finding.
"""
from __future__ import annotations

import os
import threading
import time
from threading import get_ident
from typing import Dict, List, Optional, Tuple

__all__ = ["make_lock", "make_rlock", "TrackedLock", "TrackedRLock",
           "LockOrderError", "enabled", "enable", "edges", "inversions",
           "hold_stats", "held_now", "assert_no_inversions", "reset"]


class LockOrderError(RuntimeError):
    """A tracked acquisition that is certain (same-thread re-acquire of a
    non-reentrant lock) or strongly suspected (bounded-timeout expiry on
    an inverted order) to deadlock."""


# -- global state (guarded by a PLAIN lock: the meta-lock must never be
# tracked, or recording an edge would itself record edges) ------------------
_META = threading.Lock()
_EDGES: Dict[Tuple[str, str], Dict] = {}       # (held, acquired) -> first seen
_INVERSIONS: List[Dict] = []
_FLAGGED_PAIRS: set = set()                    # dedupe: one report per pair
_HOLDS: Dict[str, Dict] = {}                   # name -> count/max_ms/total_ms

_HELD = threading.local()                      # per-thread [(name, t0), ...]

_ENABLED: Optional[bool] = None


def enabled() -> bool:
    """True when new locks should be tracked: ``MXTPU_LOCKCHECK`` truthy,
    or a ``MXTPU_CHAOS`` spec is present (chaos stress runs always get the
    sanitizer), unless overridden by :func:`enable`. Consulted at lock
    *creation* time, so flipping it mid-run only affects new locks."""
    if _ENABLED is not None:
        return _ENABLED
    from .util import getenv  # ENV_VARS is the one defaults catalog
    if getenv("MXTPU_LOCKCHECK") not in ("", "0", "false", "off"):
        return True
    return bool(os.environ.get("MXTPU_CHAOS"))


def enable(on: bool = True) -> None:
    """Programmatic override of the env switch (tests)."""
    global _ENABLED
    _ENABLED = bool(on)


def _hold_threshold_ms() -> float:
    from .util import getenv
    try:
        return float(getenv("MXTPU_LOCKCHECK_HOLD_MS"))
    except (TypeError, ValueError):
        return 250.0


def _acquire_timeout_s() -> float:
    from .util import getenv
    try:
        return float(getenv("MXTPU_LOCKCHECK_TIMEOUT_S"))
    except (TypeError, ValueError):
        return 5.0


def _held_stack() -> List[Tuple[str, float, "TrackedLock"]]:
    """The calling thread's (name, t0, lock) entries, outermost first.
    Entries whose lock a DIFFERENT thread has since released (cross-
    thread ``Lock.release`` is legal) are purged lazily here, so a stale
    entry can neither fake a self-deadlock nor feed bogus edges."""
    stack = getattr(_HELD, "stack", None)
    if stack is None:
        stack = _HELD.stack = []
    me = get_ident()
    stale = [i for i, (_n, _t, lk) in enumerate(stack)
             if lk._owner != me]
    for i in reversed(stale):
        del stack[i]
    return stack


def held_now() -> List[str]:
    """Names of tracked locks the calling thread holds, outermost first."""
    return [name for name, _t, _lk in _held_stack()]


def _emit(kind: str, severity: str, **fields) -> None:
    """Publish on the telemetry bus. Lazy import (this module is a leaf
    every runtime package imports) and re-entrancy guarded: the bus's own
    lock is tracked, and a hold/inversion fired while reporting one must
    not recurse."""
    if getattr(_HELD, "reporting", False):
        return
    _HELD.reporting = True
    try:
        from .telemetry import events as _tele
        from .telemetry import metrics as _tmetrics
        _tele.emit(kind, severity=severity, **fields)
        if kind == "concurrency.inversion":
            _tmetrics.counter("mxtpu_lockcheck_inversions_total",
                              "Lock-order inversions observed live").inc()
    except Exception:  # noqa: BLE001 — the sanitizer must never crash
        pass           # the locking subsystem it observes
    finally:
        _HELD.reporting = False


class TrackedLock:
    """Order-tracking wrapper with ``threading.Lock`` semantics."""

    _reentrant = False

    def __init__(self, name: str):
        self.name = name
        self._inner = self._make_inner()
        #: ident of the thread currently holding this lock (None = free);
        #: lets the per-thread held stacks detect cross-thread releases
        self._owner: Optional[int] = None

    @staticmethod
    def _make_inner():
        return threading.Lock()

    # -- bookkeeping ----------------------------------------------------
    def _check_order(self) -> bool:
        """Record edges held→self; returns True when this acquisition
        reverses an already-recorded order (inversion)."""
        held = held_now()
        thread = threading.current_thread().name
        inverted = False
        with _META:
            for h in held:
                if h == self.name:
                    continue
                fwd = (h, self.name)
                rev = (self.name, h)
                if rev not in _EDGES and fwd not in _EDGES:
                    # an acquisition that reverses a recorded order is
                    # evidence of the bug, not a new legal order: banking
                    # it as an edge would make the VICTIM thread's
                    # consistent re-acquire look inverted too, bounding
                    # both halves of a real deadlock and turning the
                    # flag-the-culprit contract into a timeout race
                    _EDGES[fwd] = {"thread": thread,
                                   "ts": round(time.time(), 6)}
                if rev in _EDGES:
                    inverted = True
                    if frozenset(fwd) not in _FLAGGED_PAIRS:
                        _FLAGGED_PAIRS.add(frozenset(fwd))
                        _INVERSIONS.append({
                            "held": h, "acquiring": self.name,
                            "thread": thread,
                            "reverse_seen_on": _EDGES[rev]["thread"],
                            "ts": round(time.time(), 6)})
        return inverted

    def _note_inversion(self, held_name: str) -> None:
        _emit("concurrency.inversion", "error",
              held=held_name, acquiring=self.name,
              thread=threading.current_thread().name)

    # -- Lock protocol --------------------------------------------------
    def acquire(self, blocking: bool = True, timeout: float = -1):
        stack = _held_stack()
        if not self._reentrant and any(lk is self for _n, _t, lk in stack):
            with _META:
                _INVERSIONS.append({
                    "held": self.name, "acquiring": self.name,
                    "thread": threading.current_thread().name,
                    "self_deadlock": True, "ts": round(time.time(), 6)})
            self._note_inversion(self.name)
            raise LockOrderError(
                f"lock {self.name!r} re-acquired on the same thread — "
                "certain self-deadlock (use an RLock if re-entry is "
                "intended)")
        inverted = self._check_order()
        if inverted:
            held = [n for n in held_now() if n != self.name]
            self._note_inversion(held[-1] if held else "?")
        if not blocking:
            ok = self._inner.acquire(False)
        elif inverted:
            # an inverted acquire may be the losing half of a real
            # deadlock: bound it so the process flags-and-fails instead
            # of hanging (the two-lock fixture test's contract)
            bound = _acquire_timeout_s() if timeout in (-1, None) \
                else min(timeout, _acquire_timeout_s())
            ok = self._inner.acquire(True, bound)
            if not ok:
                raise LockOrderError(
                    f"lock {self.name!r} not acquired within "
                    f"{bound:.1f}s after a lock-order inversion while "
                    f"holding {held_now()!r} — likely deadlock")
        else:
            ok = (self._inner.acquire(True) if timeout in (-1, None)
                  else self._inner.acquire(True, timeout))
        if ok:
            self._owner = get_ident()
            stack.append((self.name, time.perf_counter(), self))
        return ok

    def release(self):
        # release the inner lock FIRST: contenders must not additionally
        # stall behind the hold-time bookkeeping/telemetry below (the
        # sanitizer must not inflate the very latency it measures), and
        # an illegal release raises before any state is touched
        stack = _held_stack()
        idx = next((i for i in range(len(stack) - 1, -1, -1)
                    if stack[i][2] is self), None)
        self._inner.release()
        mine = idx is not None
        if mine:
            _name, t0, _lk = stack.pop(idx)
        if self._owner == get_ident() or not mine:
            # freed by its owner, or a legal cross-thread hand-off: the
            # previous owner's stale stack entry purges lazily via
            # _held_stack() once _owner no longer matches it
            if not (self._reentrant
                    and any(lk is self for _n, _t, lk in stack)):
                self._owner = None
        if mine:
            hold_ms = (time.perf_counter() - t0) * 1e3
            with _META:
                ent = _HOLDS.setdefault(self.name, {
                    "count": 0, "max_ms": 0.0, "total_ms": 0.0})
                ent["count"] += 1
                ent["max_ms"] = max(ent["max_ms"], hold_ms)
                ent["total_ms"] += hold_ms
            if hold_ms >= _hold_threshold_ms():
                _emit("concurrency.hold", "warning", lock=self.name,
                      hold_ms=round(hold_ms, 3))

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()

    def __repr__(self):
        return f"{type(self).__name__}({self.name!r})"


class TrackedRLock(TrackedLock):
    """Order-tracking wrapper with ``threading.RLock`` semantics (same-
    thread re-acquisition is legal and records no self edge)."""

    _reentrant = True

    @staticmethod
    def _make_inner():
        return threading.RLock()

    def _check_order(self) -> bool:
        if any(lk is self for _n, _t, lk in _held_stack()):
            return False   # re-entry: no new edges, no inversion
        return super()._check_order()


def make_lock(name: str):
    """A named lock: plain ``threading.Lock`` normally, a
    :class:`TrackedLock` under lockcheck. ``name`` should match the
    static analysis' lock id (``Class._attr`` / ``module._VAR``) so the
    runtime graph and the MX802 graph cross-check by name."""
    return TrackedLock(name) if enabled() else threading.Lock()


def make_rlock(name: str):
    """Reentrant variant of :func:`make_lock`."""
    return TrackedRLock(name) if enabled() else threading.RLock()


# -- inspection -------------------------------------------------------------

def edges() -> List[Dict]:
    """Observed acquisition-order edges ``{held, acquired, thread, ts}``."""
    with _META:
        return [{"held": a, "acquired": b, **info}
                for (a, b), info in _EDGES.items()]


def inversions() -> List[Dict]:
    """Recorded lock-order inversions (one per unordered lock pair)."""
    with _META:
        return [dict(d) for d in _INVERSIONS]


def hold_stats() -> Dict[str, Dict]:
    """Per-lock hold accounting ``{name: {count, max_ms, total_ms}}``."""
    with _META:
        return {k: dict(v) for k, v in _HOLDS.items()}


def assert_no_inversions() -> None:
    """Raise if any inversion was observed — the chaos/lockcheck CI
    smoke's in-process gate (the stream-level twin greps the telemetry
    JSONL for ``concurrency.inversion`` via ``tools/telemetry_check.py
    --forbid``)."""
    inv = inversions()
    if inv:
        raise LockOrderError(
            f"{len(inv)} lock-order inversion(s) observed:\n" +
            "\n".join(f"  {d}" for d in inv[:10]))


def reset() -> None:
    """Drop recorded edges/inversions/hold stats (tests). Live locks keep
    tracking; per-thread held stacks are untouched."""
    with _META:
        _EDGES.clear()
        _INVERSIONS.clear()
        _FLAGGED_PAIRS.clear()
        _HOLDS.clear()

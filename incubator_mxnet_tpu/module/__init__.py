"""Module API — the legacy symbolic training loop.

Reference parity: ``python/mxnet/module/`` (``BaseModule.fit``, ``Module``)
over ``GraphExecutor`` via ``simple_bind`` — SURVEY §2.7, call stack §3.5.
This is what ``example/image-classification/train_mnist.py`` uses.

TPU-native design: one Executor = one jitted XLA callable + vjp; the
``DataParallelExecutorGroup`` batch-slicing disappears (SPMD sharding does
data parallelism below this API — or use parallel.ShardedTrainer for the
modern path).
"""
from __future__ import annotations

import logging
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import numpy as onp

from ..base import MXNetError
from ..context import Context, cpu, current_context
from ..ndarray import NDArray, array
from .. import initializer as init_mod
from .. import metric as metric_mod
from .. import model as model_mod
from .. import optimizer as opt_mod

__all__ = ["BaseModule", "Module"]


class BaseModule:
    """Shared training-loop driver (reference: base_module.py)."""

    def __init__(self, logger=logging):
        self.logger = logger
        self.binded = False
        self.params_initialized = False
        self.optimizer_initialized = False

    # fit ------------------------------------------------------------------
    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None,
            kvstore: str = "local", optimizer: str = "sgd",
            optimizer_params=(("learning_rate", 0.01),),
            eval_end_callback=None, initializer=None,
            arg_params=None, aux_params=None, allow_missing: bool = False,
            force_init: bool = False, begin_epoch: int = 0,
            num_epoch: Optional[int] = None, validation_metric=None,
            monitor=None, sparse_row_id_fn=None):
        if num_epoch is None:
            raise MXNetError("fit requires num_epoch")
        if not self.binded:
            self.bind(data_shapes=train_data.provide_data,
                      label_shapes=train_data.provide_label, for_training=True)
        if not self.params_initialized or force_init:
            self.init_params(initializer or init_mod.Xavier(magnitude=2.0),
                             arg_params, aux_params, allow_missing, force_init)
        if not self.optimizer_initialized:
            self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                                optimizer_params=optimizer_params)
        if not isinstance(eval_metric, metric_mod.EvalMetric):
            eval_metric = metric_mod.create(eval_metric)
        validation_metric = validation_metric or eval_metric
        if monitor is not None:
            self.install_monitor(monitor)

        for epoch in range(begin_epoch, num_epoch):
            eval_metric.reset()
            nbatch = 0
            train_data.reset()
            for batch in train_data:
                if monitor is not None:
                    monitor.tic()
                self.forward_backward(batch)
                if monitor is not None:
                    # capture BEFORE update(): the stats must reflect the
                    # weights the monitored forward actually used
                    monitor.toc_print()
                self.update()
                self.update_metric(eval_metric, batch.label)
                if batch_end_callback is not None:
                    param = model_mod.BatchEndParam(
                        epoch=epoch, nbatch=nbatch, eval_metric=eval_metric,
                        locals=None)
                    for cb in _as_list(batch_end_callback):
                        cb(param)
                nbatch += 1
            for name, val in eval_metric.get_name_value():
                self.logger.info("Epoch[%d] Train-%s=%f", epoch, name, val)
            if epoch_end_callback is not None:
                arg_p, aux_p = self.get_params()
                for cb in _as_list(epoch_end_callback):
                    cb(epoch, self.symbol, arg_p, aux_p)
            if eval_data is not None:
                res = self.score(eval_data, validation_metric)
                for name, val in res:
                    self.logger.info("Epoch[%d] Validation-%s=%f", epoch, name, val)

    def forward_backward(self, data_batch) -> None:
        self.forward(data_batch, is_train=True)
        self.backward()

    def score(self, eval_data, eval_metric, num_batch=None, reset=True):
        if not isinstance(eval_metric, metric_mod.EvalMetric):
            eval_metric = metric_mod.create(eval_metric)
        if reset:
            eval_data.reset()
            eval_metric.reset()
        for i, batch in enumerate(eval_data):
            if num_batch is not None and i >= num_batch:
                break
            self.forward(batch, is_train=False)
            self.update_metric(eval_metric, batch.label)
        return eval_metric.get_name_value()

    def predict(self, eval_data, num_batch=None, reset=True):
        if reset:
            eval_data.reset()
        outs = []
        for i, batch in enumerate(eval_data):
            if num_batch is not None and i >= num_batch:
                break
            self.forward(batch, is_train=False)
            outs.append(self.get_outputs()[0].asnumpy())
        return array(onp.concatenate(outs, axis=0))


def _as_list(x):
    return x if isinstance(x, (list, tuple)) else [x]


class Module(BaseModule):
    """Single-executor Module (reference: module.py Module)."""

    def __init__(self, symbol, data_names: Sequence[str] = ("data",),
                 label_names: Sequence[str] = ("softmax_label",),
                 logger=logging, context: Union[Context, Sequence[Context], None] = None,
                 work_load_list=None, fixed_param_names=None, state_names=None,
                 group2ctxs=None, compression_params=None):
        super().__init__(logger)
        self.symbol = symbol
        self._data_names = list(data_names)
        self._label_names = list(label_names or [])
        ctx = context if context is not None else current_context()
        if isinstance(ctx, (list, tuple)):
            ctx = ctx[0]  # SPMD replaces multi-ctx executor groups
        self._ctx = ctx
        self._exec = None
        self._optimizer = None
        self._opt_states: Dict[int, tuple] = {}
        self._arg_names: List[str] = []

    # -- bind / init -------------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training: bool = True,
             inputs_need_grad: bool = False, force_rebind: bool = False,
             shared_module=None, grad_req: str = "write"):
        if self.binded and not force_rebind:
            return
        shapes = {}
        for desc in data_shapes:
            name, shape = (desc.name, desc.shape) if hasattr(desc, "name") else desc
            shapes[name] = tuple(shape)
        for desc in (label_shapes or []):
            name, shape = (desc.name, desc.shape) if hasattr(desc, "name") else desc
            shapes[name] = tuple(shape)
        self._exec = self.symbol.simple_bind(
            ctx=self._ctx, grad_req=grad_req if for_training else "null",
            **shapes)
        self._arg_names = self.symbol.list_arguments()
        self._input_names = list(shapes)
        self._param_names = [n for n in self._arg_names
                             if n not in self._input_names]
        self.binded = True

    def install_monitor(self, mon) -> None:
        """Attach a :class:`~incubator_mxnet_tpu.monitor.Monitor` to the
        bound executor (reference: BaseModule.install_monitor)."""
        if not self.binded:
            raise MXNetError("call bind before install_monitor")
        mon.install(self._exec)

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing: bool = False, force_init: bool = False,
                    allow_extra: bool = False):
        if self.params_initialized and not force_init:
            return
        if not self.binded:
            raise MXNetError("call bind before init_params")
        if arg_params is None and getattr(self, "_preloaded", None):
            # Module.load path: checkpoint params take the arg_params slot
            arg_params, aux_params = self._preloaded
        initializer = initializer or init_mod.Uniform(0.01)
        for name in self._param_names:
            arr = self._exec.arg_dict[name]
            if arg_params and name in arg_params:
                arr._set_data(arg_params[name]._data)
            else:
                init_arr = NDArray(arr._data)
                initializer(init_mod.InitDesc(name), init_arr)
                arr._set_data(init_arr._data)
        self.params_initialized = True

    def get_params(self) -> Tuple[Dict[str, NDArray], Dict[str, NDArray]]:
        args = {n: self._exec.arg_dict[n].copy() for n in self._param_names}
        return args, dict(self._exec.aux_dict)

    def set_params(self, arg_params, aux_params=None, allow_missing=False,
                   force_init=True, allow_extra=False):
        self.init_params(arg_params=arg_params, aux_params=aux_params,
                         allow_missing=allow_missing, force_init=force_init)

    def init_optimizer(self, kvstore: str = "local", optimizer: str = "sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init: bool = False):
        if isinstance(optimizer, str):
            params = dict(optimizer_params)
            if "rescale_grad" not in params:
                # reference parity (module.py init_optimizer): the executor's
                # backward yields batch-SUMMED gradients, so the optimizer
                # rescales by 1/batch_size unless the caller overrode it.
                batch_size = 0
                if self.binded and self._input_names:
                    first = self._exec.arg_dict.get(self._input_names[0])
                    if first is not None and first.ndim > 0:
                        batch_size = int(first.shape[0])
                if batch_size:
                    params["rescale_grad"] = 1.0 / batch_size
            optimizer = opt_mod.create(optimizer, **params)
        self._optimizer = optimizer
        self.optimizer_initialized = True

    # -- compute -----------------------------------------------------------
    def forward(self, data_batch, is_train: Optional[bool] = None):
        feeds = {}
        for name, arr in zip(self._data_names, data_batch.data):
            feeds[name] = arr
        if self._label_names and data_batch.label:
            for name, arr in zip(self._label_names, data_batch.label):
                feeds[name] = arr
        self._exec.forward(is_train=bool(is_train), **feeds)

    def backward(self, out_grads=None):
        self._exec.backward(out_grads)

    def update(self):
        for i, name in enumerate(self._param_names):
            w = self._exec.arg_dict[name]
            g = self._exec.grad_dict.get(name)
            if g is None:
                continue
            if i not in self._opt_states:
                self._opt_states[i] = \
                    self._optimizer.create_state_multi_precision(i, w)
            self._opt_states[i] = self._optimizer.update(i, w, g, self._opt_states[i])

    def get_outputs(self, merge_multi_context: bool = True) -> List[NDArray]:
        return self._exec.outputs

    def get_input_grads(self, merge_multi_context: bool = True):
        return [self._exec.grad_dict.get(n) for n in self._data_names]

    def update_metric(self, eval_metric, labels, pre_sliced: bool = False):
        eval_metric.update_dict(
            {n: l for n, l in zip(self._label_names, labels or [])},
            {o_name: o for o_name, o in zip(self.symbol.list_outputs(),
                                            self._exec.outputs)})

    # -- checkpoint --------------------------------------------------------
    def save_checkpoint(self, prefix: str, epoch: int,
                        save_optimizer_states: bool = False):
        arg_params, aux_params = self.get_params()
        model_mod.save_checkpoint(prefix, epoch, self.symbol, arg_params,
                                  aux_params)

    @staticmethod
    def load(prefix: str, epoch: int, load_optimizer_states: bool = False,
             **kwargs) -> "Module":
        sym, arg_params, aux_params = model_mod.load_checkpoint(prefix, epoch)
        mod = Module(sym, **kwargs)
        mod._preloaded = (arg_params, aux_params)
        return mod

    @property
    def data_names(self):
        return self._data_names

    @property
    def output_names(self):
        return self.symbol.list_outputs()

"""Automatic symbol naming.

Reference counterpart: ``python/mxnet/name.py (NameManager, Prefix)`` — the
scope that turns ``FullyConnected(...)`` into ``fullyconnected0`` and, under
``with mx.name.Prefix('encoder_'):``, into ``encoder_fullyconnected0``.
``symbol._auto_name`` consults the innermost active manager.
"""
from __future__ import annotations

import threading
from typing import Dict, Optional

__all__ = ["NameManager", "Prefix"]


class NameManager:
    """Counts per op-type hint and yields ``<hint><n>`` names. Use as a
    context manager to install; nesting restores the outer manager."""

    _local = threading.local()

    def __init__(self):
        self._counter: Dict[str, int] = {}

    def get(self, name: Optional[str], hint: str) -> str:
        if name:
            return name
        n = self._counter.setdefault(hint, 0)
        self._counter[hint] = n + 1
        return f"{hint}{n}"

    @classmethod
    def current(cls) -> "NameManager":
        stack = getattr(cls._local, "stack", None)
        if stack:
            return stack[-1]
        if not hasattr(cls._local, "default"):
            cls._local.default = NameManager()
        return cls._local.default

    def __enter__(self):
        if not hasattr(self._local, "stack"):
            NameManager._local.stack = []
        NameManager._local.stack.append(self)
        return self

    def __exit__(self, *exc):
        NameManager._local.stack.pop()


class Prefix(NameManager):
    """Prepend a fixed prefix to every auto-generated name in scope."""

    def __init__(self, prefix: str):
        super().__init__()
        self._prefix = prefix

    def get(self, name: Optional[str], hint: str) -> str:
        if name:
            return name
        return self._prefix + super().get(None, hint)

#!/usr/bin/env python
"""Fusion-aware, device-blind autotuner over the model-family configs.

TVM's argument (arXiv 1802.04799) applied to this runtime: the remaining
MFU lives in *searching* configuration space over the compiled graph,
not hand-picking one env recipe per round. ``bert_sweep.py`` runs eight
hand-listed variants on real hardware; this driver generalizes that list
into a declared search space (remat policy × flash block size ×
batch/bucket geometry × embedding-gradient path), evaluates candidates
**in-process with zero XLA compiles** — every candidate is traced
(``ShardedTrainer.prepare`` + ``jax.make_jaxpr`` for train families, the
un-warmed ``CompiledModel`` for serving families) and priced by
``analysis.hlo.cost`` — and persists the winner per
``(family, mesh_shape, chip)`` into the CRC-manifested
:class:`~incubator_mxnet_tpu.autotune.AutotuneCache` that BOTH
``parallel.ShardedTrainer`` and ``serve.CompiledModel`` consult at build
time. The search is a deterministic function of the graph, so the same
space always elects the same winner — bankable and CI-gateable with no
hardware, exactly like PERF_PROXY.json.

Score: a roofline proxy over the cost table plus the compile-ledger
dimensions (docs/architecture.md "Autotuning")::

    steady_s = max(flops/PEAK_FLOPS, hbm_bytes/PEAK_BW)
               + comm_bytes/ICI_BW + LAUNCH_S * fusion_groups
    warmup_s = COMPILE_S * graphs            # the ledger's warmup count
    score    = tokens_per_step / (steady_s + warmup_s / AMORTIZE_STEPS)

Candidates that cannot change the traced graph on this backend (e.g.
flash block sizes on CPU, where Pallas falls back to XLA attention) tie,
and the deterministic enumeration order breaks the tie — still the same
winner twice.

Memory feasibility: when ``MXTPU_HBM_BUDGET`` is set, every candidate's
whole-ladder residency (``analysis.hlo`` liveness scan,
``ladder_peak_bytes``) is checked against it and infeasible candidates
are scored-but-never-elected (reported, no silent caps) — the search
can expand batch/bucket geometry without proposing configs that OOM
the chip.

    python -m benchmark.autotune --families bert --budget 16 \
        --cache-dir autotune_cache
    python -m benchmark.autotune --families lenet --budget 6 \
        --cache-dir autotune_cache --gate      # the CI autotune-smoke job

``bert_sweep.py`` now derives its hardware-sweep VARIANTS from this
file's :func:`bench_variants` — one source of truth for the dimensions.
"""
from __future__ import annotations

import argparse
import itertools
import json
import os
import sys
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:  # `python benchmark/autotune.py` direct invocation
    sys.path.insert(0, REPO)


# ---------------------------------------------------------------------------
# the search space — ONE declaration, shared with bert_sweep.py
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class Dim:
    """One tunable dimension: ``env`` knobs overlay the trace
    (``""`` = leave unset/auto), ``geom`` dims size the probe
    batch/bucket geometry, ``struct`` dims parameterize the model
    build (remat)."""

    name: str
    kind: str                    # "env" | "geom" | "struct"
    values: tuple
    env: Optional[str] = None    # the knob, for kind == "env"
    note: str = ""


#: the declared dimensions, in deterministic enumeration order
DIMS: Dict[str, Dim] = {d.name: d for d in (
    Dim("remat", "struct", (False, True),
        note="jax.checkpoint per encoder layer — trades recompute for HBM"),
    Dim("flash_bk", "env", ("", "128", "256", "512"), env="MXTPU_FLASH_BK",
        note="flash-attention key/value block size ('' = auto)"),
    Dim("embed_grad", "env", ("0", "1"), env="MXTPU_EMBED_ONEHOT_GRAD",
        note="embedding weight grad: scatter-add (0) vs one-hot matmul (1)"),
    Dim("batch", "geom", (2, 4, 8),
        note="probe batch size / batch-bucket geometry"),
    Dim("seq", "geom", (16, 32),
        note="probe sequence length / seq-bucket geometry"),
    Dim("quantize", "struct", ("off", "int8"),
        note="serving precision: float zoo vs calibrated int8 twin "
             "(models.quantized_smoke); candidates whose quantized "
             "graphs carry MX71x errors are scored but never elected"),
)}

#: per-family dimension subsets + probe kind. Train families score the
#: full fwd+bwd+optimizer step graph (the 0.40-MFU workload); serve-only
#: families score their bucketed inference graphs.
FAMILY_SPACES: Dict[str, Dict[str, Any]] = {
    "bert": {"kind": "train",
             "dims": ("remat", "flash_bk", "embed_grad", "batch", "seq")},
    "lenet": {"kind": "train", "dims": ("batch",)},
    "bert_encoder": {"kind": "serve",
                     "dims": ("flash_bk", "batch", "seq", "quantize")},
    "transformer_encoder": {"kind": "serve",
                            "dims": ("flash_bk", "batch", "seq")},
    "nmt_encoder": {"kind": "serve",
                    "dims": ("flash_bk", "embed_grad", "batch", "seq",
                             "quantize")},
}

#: real-hardware geometry the subprocess sweep (bert_sweep.py) probes —
#: expressed through bench.py's env knobs, values from the same
#: dimensions scaled to the headline workload
BENCH_GEOMETRY = {"batch": (4, 8, 16, 32), "seq": (512, 1024)}


def bench_variants() -> List[Tuple[str, Dict[str, str]]]:
    """The bert_sweep.py VARIANTS list, derived from :data:`DIMS` and
    :data:`BENCH_GEOMETRY` (BASELINE.md round-3 prepared sweep: batch/
    remat rescan under the adaptive flash tiles, the BK=256 variant, and
    the one-hot embedding-gradient path)."""
    onehot = DIMS["embed_grad"].env
    bk = DIMS["flash_bk"].env
    assert "256" in DIMS["flash_bk"].values
    batches, seqs = BENCH_GEOMETRY["batch"], BENCH_GEOMETRY["seq"]
    return [
        ("default-B8", {}),
        ("embed-onehot-grad", {onehot: "1"}),
        ("flash-BK256", {bk: "256"}),
        (f"B{batches[2]}", {"MXTPU_BENCH_BATCH": str(batches[2])}),
        (f"B{batches[2]}-remat", {"MXTPU_BENCH_BATCH": str(batches[2]),
                                  "MXTPU_BENCH_REMAT": "1"}),
        (f"B{batches[3]}-remat", {"MXTPU_BENCH_BATCH": str(batches[3]),
                                  "MXTPU_BENCH_REMAT": "1"}),
        (f"B{batches[1]}-onehot+BK256", {onehot: "1", bk: "256"}),
        # same tokens/step as the headline config, doubled sequence:
        # probes whether the flash tiles hold their efficiency as the
        # attention share of credited FLOPs grows (L divides the tiles)
        (f"B{batches[0]}-L{seqs[1]}", {"MXTPU_BENCH_BATCH": str(batches[0]),
                                       "MXTPU_BENCH_SEQ": str(seqs[1])}),
    ]


def candidates(family: str,
               budget: Optional[int] = None) -> List[Dict[str, Any]]:
    """Deterministic candidate list: the cartesian product of the
    family's dimensions in declared order, truncated to ``budget``.
    Truncation is reported by the caller (no silent caps)."""
    space = FAMILY_SPACES[family]
    dims = [DIMS[n] for n in space["dims"]]
    out = [dict(zip((d.name for d in dims), combo))
           for combo in itertools.product(*(d.values for d in dims))]
    return out[:budget] if budget else out


# ---------------------------------------------------------------------------
# scoring — deterministic roofline proxy over the cost table
# ---------------------------------------------------------------------------

_LAUNCH_S = 2e-6                 # per fused-kernel dispatch overhead proxy
_COMPILE_S = 30.0                # per-graph warmup compile proxy (ledger)
_AMORTIZE_STEPS = 10000.0        # steps a banked config is expected to run


def _peaks() -> Tuple[float, float, float]:
    # THE shared peak table (util.roofline_peaks): bench.py's MFU
    # accounting, this score, and telemetry.goodput's predicted_mfu all
    # read one source, so a chip-kind correction can never diverge them
    from incubator_mxnet_tpu.util import roofline_peaks
    return roofline_peaks()


def score(metrics: Dict[str, Any],
          measured: Optional[Dict[str, float]] = None) -> float:
    """tokens/sec under the roofline proxy — higher is better. A pure
    function of the cost-table metrics and the (fixed) peak constants,
    so candidate ranking is deterministic by construction.

    ``measured`` folds a goodput window's attribution into the score
    (the flight director's rescoring hook, TVM's learned-cost-model
    argument in miniature): the window's ``collective`` / ``input_wait``
    / ``host`` wall fractions, priced relative to its ``compute``
    fraction, re-weight the analytic terms — measured communication can
    only *raise* the analytic comm estimate (the model stays a lower
    bound), and input/host time the analytic model assumes away is added
    outright. ``None`` (the default, and every pre-existing caller) is
    the original expression bit for bit."""
    peak_flops, peak_bw, ici_bw = _peaks()
    compute_s = metrics["flops_per_step"] / peak_flops
    mem_s = metrics["hbm_bytes_per_step"] / peak_bw
    comm_s = metrics["comm_bytes_per_step"] / ici_bw
    launch_s = _LAUNCH_S * metrics["fusion_groups"]
    device_s = max(compute_s, mem_s)
    steady_s = device_s + comm_s + launch_s
    if measured:
        f_comp = max(float(measured.get("compute", 0.0)), 1e-6)
        per_compute = device_s / f_comp   # 1.0 measured fraction in secs
        comm_meas = per_compute * float(measured.get("collective", 0.0))
        input_s = per_compute * float(measured.get("input_wait", 0.0))
        host_s = per_compute * float(measured.get("host", 0.0))
        steady_s = (device_s + max(comm_s, comm_meas) + input_s + host_s
                    + launch_s)
    warmup_s = _COMPILE_S * metrics["graphs"]
    return metrics["tokens_per_step"] / (steady_s
                                         + warmup_s / _AMORTIZE_STEPS)


# ---------------------------------------------------------------------------
# candidate evaluation — trace-only, zero XLA compiles
# ---------------------------------------------------------------------------

def _train_probe(family: str, cfg: Dict[str, Any], guarded: bool = False):
    """(trainer, batch, tokens) for a train-family candidate — tiny zoo
    instance at the candidate's geometry; ``prepare()`` below builds the
    step WITHOUT dispatching, so pricing it never XLA-compiles. Probe
    trainers live for one trace (or the 3-step gate replay) — nothing to
    checkpoint. ``guarded=True`` (the --gate replay) attaches a
    StepGuard AND an LR scheduler so the one-graph contract is actually
    exercised: an unfused regression would dispatch the separate jitted
    finite check and fail the graph count."""  # mxlint: disable-file=MX401
    import jax
    import numpy as onp

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import fault, gluon, lr_scheduler, models, \
        parallel

    B = int(cfg.get("batch", 2))
    L = int(cfg.get("seq", 16))
    mx.random.seed(11)
    mesh = parallel.make_mesh(devices=jax.devices()[:1])
    rng = onp.random.RandomState(0)
    extra: Dict[str, Any] = {}
    if guarded:
        extra["guard"] = fault.StepGuard(policy="warn")
    if family == "bert":
        vocab, P = 1000, max(1, round(0.15 * L))
        net = models.get_bert("bert_2_128_2", vocab_size=vocab,
                              max_length=32, dropout=0.1,
                              remat=bool(cfg.get("remat", False)))
        net.initialize()
        ids = rng.randint(0, vocab, (B, L)).astype("int32")
        tt = rng.randint(0, 2, (B, L)).astype("int32")
        vl = onp.full((B,), L, "float32")
        pos = rng.randint(0, L, (B, P)).astype("int32")
        mlm_lab = rng.randint(0, vocab, (B, P)).astype("float32")
        mlm_w = onp.ones((B, P), "float32")
        nsp = rng.randint(0, 2, (B,)).astype("float32")
        batch = (ids, tt, vl, pos, mlm_lab, mlm_w, nsp)
        opt_params: Dict[str, Any] = {"learning_rate": 1e-4}
        if guarded:
            opt_params["lr_scheduler"] = lr_scheduler.CosineScheduler(
                max_update=1000, base_lr=1e-4)
        trainer = parallel.ShardedTrainer(
            net, models.bert_pretrain_loss, "adamw",
            opt_params, mesh=mesh,
            rules=models.bert_sharding_rules(), n_labels=3,
            autotune_key="bert", **extra)
        return trainer, batch, B * L
    if family == "lenet":
        net = models.LeNet()
        net.initialize()
        ce = gluon.loss.SoftmaxCrossEntropyLoss()
        x = rng.rand(B, 1, 28, 28).astype("float32")
        y = rng.randint(0, 10, (B,)).astype("float32")
        opt_params = {"learning_rate": 0.05, "momentum": 0.9}
        if guarded:
            opt_params["lr_scheduler"] = lr_scheduler.FactorScheduler(
                step=100, factor=0.9, base_lr=0.05)
        trainer = parallel.ShardedTrainer(
            net, lambda out, label: ce(out, label), "sgd",
            opt_params, mesh=mesh, autotune_key="lenet", **extra)
        return trainer, (x, y), B
    raise KeyError(f"no train probe for family {family!r}")


def evaluate(family: str, cfg: Dict[str, Any]) -> Dict[str, Any]:
    """Price one candidate: apply its env dims for exactly the trace
    scope (forced — the driver measures the candidate, not the ambient
    shell), build the probe, and read the cost table. Returns the
    metrics dict :func:`score` consumes."""
    from incubator_mxnet_tpu import autotune as _cache_mod
    from incubator_mxnet_tpu import models
    from incubator_mxnet_tpu.analysis import hlo

    env = {DIMS[k].env: str(v) for k, v in cfg.items()
           if DIMS[k].kind == "env" and str(v) != ""}
    kind = FAMILY_SPACES[family]["kind"]
    quantized = str(cfg.get("quantize", "off")) == "int8"
    quant_errors = 0
    with _cache_mod.applied({"config": {"env": env}}, force=True):
        if kind == "train":
            trainer, batch, tokens = _train_probe(family, cfg)
            trainer.prepare(*batch)
            rep = hlo.cost(trainer, sample_args=batch)
        else:
            if quantized:
                smoke = models.quantized_smoke(family,
                                               batch=cfg.get("batch"),
                                               seq=cfg.get("seq"))
            else:
                smoke = models.hlo_smoke(family, batch=cfg.get("batch"),
                                         seq=cfg.get("seq"))
            max_g = max(8, smoke["table"].num_buckets())
            rep = hlo.cost(smoke["compiled"], max_graphs=max_g)
            if quantized:
                # precision-flow gate: an int8 candidate whose graphs
                # carry MX71x errors (silent promotion, missing
                # calibration, q/dq hazards) is priced like any other
                # but marked dirty — search() never elects it
                qrep = hlo.verify(smoke["compiled"], max_graphs=max_g)
                quant_errors = sum(1 for d in qrep.errors
                                   if d.code.startswith("MX71"))
            tokens = (int(cfg.get("batch") or 2)
                      * int(cfg.get("seq") or 16))
    head = rep.head
    if head is None:
        raise RuntimeError(f"candidate {cfg} traced zero graphs for "
                           f"{family!r} (skipped: {rep.skipped})")
    return {
        "flops_per_step": rep.model_flops_per_step(),
        "bytes_per_step": rep.bytes_per_step(),
        "hbm_bytes_per_step": rep.bytes_per_step() + head.activation_bytes,
        "comm_bytes_per_step": rep.comm_bytes_per_step(),
        "fusion_groups": head.fusion_groups,
        "fusion_candidates": head.fusion_candidates,
        "graphs": len(rep.rows),
        "tokens_per_step": tokens,
        # residency (liveness scan): the worst graph's peak and the
        # whole-ladder footprint — what the memory-feasibility
        # constraint checks against MXTPU_HBM_BUDGET
        "peak_live_bytes": rep.peak_live_bytes(),
        "ladder_peak_bytes": rep.ladder_peak_bytes(),
        # MX71x error count over the quantized graphs (0 for float
        # candidates) — the precision-flow feasibility input
        "quant_errors": quant_errors,
    }


def winner_config(family: str, cfg: Dict[str, Any]) -> Dict[str, Any]:
    """The cache-entry config for one winning candidate: env knobs under
    ``env`` (what ``autotune.applied`` overlays at build time), probe
    geometry and structural choices recorded alongside for the operator."""
    env = {DIMS[k].env: str(v) for k, v in cfg.items()
           if DIMS[k].kind == "env" and str(v) != ""}
    geometry = {k: v for k, v in cfg.items() if DIMS[k].kind == "geom"}
    struct = {k: v for k, v in cfg.items() if DIMS[k].kind == "struct"}
    return {"env": env, "geometry": geometry, "struct": struct}


def search(family: str, budget: Optional[int] = None, cache=None,
           mesh_key: str = "any",
           measured: Optional[Dict[str, float]] = None) -> Dict[str, Any]:
    """Evaluate the family's candidate list and (optionally) bank the
    winner. Deterministic: same space + budget → same winner, twice
    (``measured`` is part of that determinism key — a fixed attribution
    dict re-ranks the same rows the same way; ``None`` leaves every
    result byte-identical to the pre-rescoring search)."""
    from incubator_mxnet_tpu import autotune as _cache_mod
    from incubator_mxnet_tpu import telemetry

    space = FAMILY_SPACES[family]
    full = candidates(family)
    cand = candidates(family, budget)
    # memory-feasibility constraint: a candidate whose whole-ladder
    # residency (liveness scan, deterministic) exceeds MXTPU_HBM_BUDGET
    # is scored but NEVER elected — the search can expand geometry
    # without proposing configs that OOM the chip. Unset budget =
    # unconstrained (the pre-memory-gate behavior, bit for bit).
    from incubator_mxnet_tpu.telemetry import memory as _memory
    hbm_budget = _memory.hbm_budget()
    rows = []
    for cfg in cand:
        metrics = evaluate(family, cfg)
        mem_ok = (hbm_budget is None
                  or metrics["ladder_peak_bytes"] <= hbm_budget)
        # MX711-dirty (or any MX71x-error) int8 candidate: scored,
        # reported, never elected — same contract as the memory gate
        quant_ok = metrics.get("quant_errors", 0) == 0
        rows.append({"config": dict(cfg), "metrics": metrics,
                     "score": score(metrics, measured=measured),
                     "feasible": mem_ok and quant_ok})
    feasible_i = [i for i, r in enumerate(rows) if r["feasible"]]
    if not feasible_i:
        if hbm_budget is None:
            raise RuntimeError(
                f"autotune: every candidate of {family!r} failed the "
                "MX71x precision-flow gate — recalibrate the quantized "
                "zoo or drop the quantize dim")
        raise RuntimeError(
            f"autotune: every candidate of {family!r} exceeds the "
            f"{hbm_budget / 2**20:.1f} MiB MXTPU_HBM_BUDGET (smallest "
            f"ladder peak "
            f"{min(r['metrics']['ladder_peak_bytes'] for r in rows) / 2**20:.1f}"
            " MiB) — shrink the declared geometry dims or raise the budget")
    best_i = max(feasible_i, key=lambda i: (rows[i]["score"], -i))
    best = rows[best_i]
    result = {
        "family": family, "kind": space["kind"],
        "dims": list(space["dims"]),
        "evaluated": len(rows), "space_size": len(full),
        "truncated": len(full) - len(cand),   # no silent caps
        "infeasible": len(rows) - len(feasible_i),
        "quant_infeasible": sum(
            1 for r in rows if r["metrics"].get("quant_errors", 0)),
        "hbm_budget": hbm_budget,
        "winner": best["config"], "winner_score": best["score"],
        "winner_metrics": best["metrics"],
        "rows": rows,
        "chip": _cache_mod.chip_kind(), "mesh": mesh_key,
    }
    if measured is not None:
        result["measured"] = dict(measured)
    if cache is not None:
        meta = {"dims": list(space["dims"]), "evaluated": len(rows),
                "space_size": len(full), "driver": "benchmark.autotune"}
        if measured is not None:
            meta["measured"] = dict(measured)
        result["cache_path"] = cache.put(
            family, mesh_key, _cache_mod.chip_kind(),
            winner_config(family, best["config"]), best["score"],
            meta=meta)
    telemetry.emit("autotune.search", family=family,
                   evaluated=len(rows), space_size=len(full),
                   infeasible=result["infeasible"], hbm_budget=hbm_budget,
                   winner=best["config"], score=best["score"],
                   banked=result.get("cache_path"))
    return result


# ---------------------------------------------------------------------------
# --gate: the CI autotune-smoke contract
# ---------------------------------------------------------------------------

def gate(family: str, cache_dir: str, result: Dict[str, Any]) -> List[str]:
    """Replay the banked winner through the REAL consult path and return
    a list of failures (empty = green): the cache entry must verify, the
    fresh build must consult it (hit), the tuned steady state must add
    zero post-warmup compiles on the ledger, and the consult event must
    carry the build site (ledger attribution)."""
    from incubator_mxnet_tpu import autotune as _cache_mod
    from incubator_mxnet_tpu import telemetry
    from incubator_mxnet_tpu.telemetry import compile_log

    failures: List[str] = []
    cache = _cache_mod.AutotuneCache(cache_dir)
    entry = cache.get(family, "any")
    if entry is None:
        return [f"no verified cache entry for {family!r} under "
                f"{cache_dir}"]
    prev = os.environ.get("MXTPU_AUTOTUNE_DIR")
    os.environ["MXTPU_AUTOTUNE_DIR"] = cache_dir
    try:
        kind = FAMILY_SPACES[family]["kind"]
        site = "trainer.step" if kind == "train" else "serve.compiled"
        if kind == "train":
            # guarded=True: the replay trainer carries a StepGuard + LR
            # scheduler, so "exactly one jitted graph per step" is a
            # real check — an unfused regression dispatches the separate
            # finite check and fails the count
            trainer, batch, _ = _train_probe(family, result["winner"],
                                             guarded=True)
            trainer.step(*batch)              # build + ONE warmup compile
            if trainer.autotune_entry is None:
                failures.append("trainer did not consult the cache "
                                "(autotune_entry is None)")
            compile_log.mark_warmed(site)
            for _ in range(2):
                trainer.step(*batch)
            if trainer.last_step_graphs != 1:
                failures.append(
                    f"fused step ran {trainer.last_step_graphs} graphs "
                    "per step (expected 1)")
            if not trainer._lr_fold:
                failures.append("LR schedule was not folded into the "
                                "step graph (whole-step capture broken)")
        else:
            from incubator_mxnet_tpu import models
            smoke = models.hlo_smoke(family)
            cm = smoke["compiled"]
            if cm.autotune_entry is None:
                failures.append("CompiledModel did not consult the cache "
                                "(autotune_entry is None)")
            cm.warmup()
            compile_log.mark_warmed(site)
            cm.predict(*smoke["example_args"])
        try:
            compile_log.assert_zero_post_warmup(site)
        except Exception as e:   # MXNetError with the offending records
            failures.append(f"post-warmup compile at {site}: {e}")
        consults = [e for e in telemetry.get_events("autotune.consult")
                    if e.fields.get("site") == site
                    and e.fields.get("model") == family
                    and e.fields.get("outcome") == "hit"]
        if not consults:
            failures.append(f"no autotune.consult hit event for "
                            f"site={site} model={family}")
    finally:
        if prev is None:
            os.environ.pop("MXTPU_AUTOTUNE_DIR", None)
        else:
            os.environ["MXTPU_AUTOTUNE_DIR"] = prev
    return failures


# ---------------------------------------------------------------------------

def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="benchmark/autotune.py",
        description="device-blind config search over the model families")
    ap.add_argument("--families", default="bert",
                    help="comma-separated families, or 'all' "
                         f"(known: {sorted(FAMILY_SPACES)})")
    ap.add_argument("--budget", type=int, default=None,
                    help="max candidates per family (deterministic "
                         "truncation; default MXTPU_AUTOTUNE_BUDGET)")
    ap.add_argument("--cache-dir", default=None,
                    help="bank each family's winner into this "
                         "AutotuneCache root")
    ap.add_argument("--mesh", default="any",
                    help="mesh_shape key to bank under (default 'any' — "
                         "the consult fallback every build matches)")
    ap.add_argument("--gate", action="store_true",
                    help="after the search, replay each winner through "
                         "the real consult path and fail on a missing "
                         "cache entry, a post-warmup compile, or a "
                         "missing consult event (the CI autotune-smoke "
                         "contract)")
    ap.add_argument("--out", default=None,
                    help="write the full result JSON here")
    args = ap.parse_args(argv)

    # device-blind by design: pin cpu so the search never claims the
    # single-client TPU tunnel (same dance as bench.py --proxy)
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=1").strip()
    import jax
    jax.config.update("jax_platforms", "cpu")

    if args.families == "all":
        families = sorted(FAMILY_SPACES)
    else:
        families = [f.strip() for f in args.families.split(",") if f.strip()]
        unknown = [f for f in families if f not in FAMILY_SPACES]
        if unknown:
            print(f"autotune: unknown families {unknown}; known: "
                  f"{sorted(FAMILY_SPACES)}", file=sys.stderr)
            return 2
    budget = args.budget
    if budget is None:
        budget = int(os.environ.get("MXTPU_AUTOTUNE_BUDGET", "16"))

    from incubator_mxnet_tpu import autotune as _cache_mod
    cache = (_cache_mod.AutotuneCache(args.cache_dir)
             if args.cache_dir else None)
    results, failures = {}, []
    for fam in families:
        res = search(fam, budget=budget, cache=cache, mesh_key=args.mesh)
        if res["truncated"]:
            print(f"autotune: {fam}: budget {budget} evaluated "
                  f"{res['evaluated']}/{res['space_size']} candidates "
                  f"(deterministic prefix)", file=sys.stderr)
        if res["infeasible"]:
            print(f"autotune: {fam}: {res['infeasible']}/{res['evaluated']}"
                  " candidate(s) excluded by the MXTPU_HBM_BUDGET "
                  "memory-feasibility constraint "
                  f"({res['hbm_budget']} bytes)", file=sys.stderr)
        if res["quant_infeasible"]:
            print(f"autotune: {fam}: {res['quant_infeasible']}/"
                  f"{res['evaluated']} candidate(s) excluded by the "
                  "MX71x precision-flow gate (dirty quantized graphs)",
                  file=sys.stderr)
        results[fam] = res
        if args.gate:
            if not args.cache_dir:
                failures.append(f"{fam}: --gate needs --cache-dir")
            else:
                failures.extend(f"{fam}: {f}"
                                for f in gate(fam, args.cache_dir, res))

    summary = {
        "metric": "autotune_winner_score",
        "value": {f: r["winner_score"] for f, r in results.items()},
        "unit": "proxy tokens/sec (roofline score)",
        "vs_baseline": None,
        "extra": {"winners": {f: r["winner"] for f, r in results.items()},
                  "evaluated": {f: r["evaluated"]
                                for f, r in results.items()},
                  "banked": {f: r.get("cache_path")
                             for f, r in results.items()},
                  "gate_failures": failures},
    }
    if args.out:
        tmp = f"{args.out}.tmp-{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump({"summary": summary, "results": results}, f,
                      indent=1, sort_keys=True, default=str)
            f.write("\n")
        os.replace(tmp, args.out)
    for fail in failures:
        print(f"autotune: GATE FAIL {fail}", file=sys.stderr)
    print(json.dumps(summary))
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Config sweep over the headline BERT bench (bench.py) on real hardware.

DEPRECATION NOTE: the hand-listed variant set has moved — this script's
VARIANTS now derive from ``benchmark/autotune.py``'s declared search
space (:func:`autotune.bench_variants`), the one source of truth for the
tunable dimensions. For device-blind search over the FULL space (scored
by the HLO cost model, winners banked into the autotune cache that
trainer and serve consult), use ``python -m benchmark.autotune``; keep
this script for validating banked winners on real hardware — each
variant still runs ``python bench.py`` in its own subprocess (its own
device client and compile cache) so a wedged/crashed config can't poison
the rest of the sweep. Results append to
``benchmark/sweep_results.jsonl`` and print as a table.

    python benchmark/bert_sweep.py             # the derived hardware sweep
    python benchmark/bert_sweep.py --quick     # default config only (smoke)
    python benchmark/bert_sweep.py --trace DIR # + profiler trace of default

Reference counterpart: ``benchmark/opperf`` does per-op timing; this is the
whole-step equivalent for the north-star workload (BASELINE.md protocol).
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

try:                              # package import (python -m benchmark...)
    from . import autotune as _autotune
except ImportError:               # direct script run
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import autotune as _autotune

# Derived from the autotuner's search-space declaration (the BASELINE.md
# round-3 prepared sweep: batch/remat rescan under the adaptive flash
# tiles, the BK=256 variant, the one-hot embedding-gradient path).
VARIANTS = _autotune.bench_variants()


def run_variant(name, env_delta, timeout=1200, trace=None):
    env = dict(os.environ, MXTPU_BENCH_TIMEOUT=str(timeout - 60), **env_delta)
    if trace:
        env["MXTPU_BENCH_TRACE"] = trace
    try:
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py")], env=env,
            capture_output=True, text=True, timeout=timeout)
    except subprocess.TimeoutExpired:
        return {"variant": name, "error": f"timeout {timeout}s"}
    for line in reversed(out.stdout.strip().splitlines()):
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        # bench.py's contract is one JSON *object* with these keys; anything
        # else (a stray numeric debug line, a partial record) is not a result
        if isinstance(rec, dict) and "value" in rec and "extra" in rec:
            rec["variant"] = name
            rec["env"] = env_delta
            return rec
    return {"variant": name, "error": (out.stderr or out.stdout)[-400:],
            "returncode": out.returncode}


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="default config only")
    ap.add_argument("--trace", default=None,
                    help="capture a profiler trace of the default config "
                         "into this directory")
    ap.add_argument("--only", default=None,
                    help="comma-separated variant names to run")
    args = ap.parse_args(argv)

    variants = VARIANTS[:1] if args.quick else VARIANTS
    if args.only:
        keep = set(args.only.split(","))
        unknown = keep - {v[0] for v in VARIANTS}
        if unknown:
            ap.error(f"unknown variant(s) {sorted(unknown)}; "
                     f"available: {[v[0] for v in VARIANTS]}")
        variants = [v for v in variants if v[0] in keep]
        if not variants:
            ap.error("--only selected nothing from the active set "
                     "(--quick keeps only the first variant)")

    results = []
    out_path = os.path.join(REPO, "benchmark", "sweep_results.jsonl")
    for name, delta in variants:
        trace = args.trace if (args.trace and name == "default-B8") else None
        rec = run_variant(name, delta, trace=trace)
        results.append(rec)
        with open(out_path, "a") as f:
            f.write(json.dumps(rec) + "\n")
        extra = rec.get("extra", {})
        if "error" in rec:
            print(f"{name:24s} ERROR {rec['error'][:120]}")
        else:
            print(f"{name:24s} step {extra.get('step_ms'):>8} ms   "
                  f"MFU {extra.get('mfu')}   {rec.get('value')} tok/s")
    ok = [r for r in results if "error" not in r]
    if ok:
        best = max(ok, key=lambda r: r["extra"]["mfu"])
        print(f"\nbest: {best['variant']}  MFU {best['extra']['mfu']}  "
              f"(env {best['env']})")
    return results


if __name__ == "__main__":
    main()

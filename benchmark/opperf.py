"""opperf — per-operator micro-benchmark suite.

Reference parity: ``benchmark/opperf/`` (opperf.py + nd_operations/*) — run
every registered operator (or a chosen subset) on representative shapes,
timing forward and forward+backward, and emit a machine-readable report.
This is the perf-regression gate the headline ``bench.py`` is too coarse
for.

TPU-native design: each measurement jits the op once (fwd, and
``jax.value_and_grad`` over a sum-reduction for bwd), warms the executable,
then times ``--iters`` synchronized runs. Dispatch overhead is excluded the
XLA way (block_until_ready around the loop) rather than with CUDA events.

Usage::

    python -m benchmark.opperf                       # curated default set
    python -m benchmark.opperf --ops dot,softmax     # subset
    python -m benchmark.opperf --all                 # every op with a config
    python -m benchmark.opperf --json out.json

Each row: {"op", "case", "fwd_ms", "bwd_ms", "gflops" (when known)}.
"""
from __future__ import annotations

import argparse
import json
import sys
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as onp


def _rng():
    return onp.random.RandomState(0)


# ---------------------------------------------------------------------------
# op configs: name -> list of (case_label, kwargs_builder, flops or None).
# The builder returns (args, kwargs) of NUMPY arrays / python scalars.
# ---------------------------------------------------------------------------

def _elementwise(shape=(1024, 1024)):
    return lambda: (( _rng().randn(*shape).astype("float32"),), {}), \
        float(onp.prod(shape))


def _binary(shape=(1024, 1024)):
    r = _rng()
    return lambda: ((r.randn(*shape).astype("float32"),
                     r.randn(*shape).astype("float32")), {}), \
        float(onp.prod(shape))


def op_configs() -> Dict[str, List[Tuple[str, Callable, Optional[float]]]]:
    r = _rng()
    cfg: Dict[str, List] = {}

    def add(name, case, builder, flops=None):
        cfg.setdefault(name, []).append((case, builder, flops))

    # --- matmul family (the MXU ops) ---
    for m, k, n in ((512, 512, 512), (2048, 2048, 2048)):
        add("dot", f"{m}x{k}x{n}",
            lambda m=m, k=k, n=n: ((r.randn(m, k).astype("float32"),
                                    r.randn(k, n).astype("float32")), {}),
            2.0 * m * k * n)
    add("batch_dot", "32x128x128x128",
        lambda: ((r.randn(32, 128, 128).astype("float32"),
                  r.randn(32, 128, 128).astype("float32")), {}),
        2.0 * 32 * 128 ** 3)
    add("FullyConnected", "B256_C1024_H1024",
        lambda: ((r.randn(256, 1024).astype("float32"),
                  r.randn(1024, 1024).astype("float32"),
                  r.randn(1024).astype("float32")),
                 {"num_hidden": 1024}),
        2.0 * 256 * 1024 * 1024)

    # --- conv / pool ---
    add("Convolution", "B32_C64_HW56_K3",
        lambda: ((r.randn(32, 64, 56, 56).astype("float32"),
                  r.randn(64, 64, 3, 3).astype("float32"),
                  r.randn(64).astype("float32")),
                 {"kernel": (3, 3), "num_filter": 64, "pad": (1, 1)}),
        2.0 * 32 * 64 * 56 * 56 * 64 * 9)
    add("Pooling", "B32_C64_HW56_max2",
        lambda: ((r.randn(32, 64, 56, 56).astype("float32"),),
                 {"kernel": (2, 2), "stride": (2, 2), "pool_type": "max"}))

    # --- norm / activation / softmax ---
    add("LayerNorm", "B64_L512_C1024",
        lambda: ((r.randn(64, 512, 1024).astype("float32"),
                  onp.ones(1024, "float32"), onp.zeros(1024, "float32")), {}))
    add("BatchNorm", "B64_C256_HW28",
        lambda: ((r.randn(64, 256, 28, 28).astype("float32"),
                  onp.ones(256, "float32"), onp.zeros(256, "float32"),
                  onp.zeros(256, "float32"), onp.ones(256, "float32")), {}))
    add("softmax", "B64_L512_V32k",
        lambda: ((r.randn(64, 512, 32768).astype("float32"),), {}))
    add("Activation", "relu_1Melem",
        lambda: ((r.randn(1024, 1024).astype("float32"),),
                 {"act_type": "relu"}))

    # --- elementwise / binary / reduce ---
    b, f = _binary()
    add("broadcast_add", "1024x1024", b, f)
    b, f = _binary()
    add("broadcast_mul", "1024x1024", b, f)
    e, f = _elementwise()
    add("exp", "1024x1024", e, f)
    e, f = _elementwise()
    add("sqrt", "1024x1024", e, f)
    add("sum", "1024x1024_axis1",
        lambda: ((r.randn(1024, 1024).astype("float32"),), {"axis": 1}))
    add("transpose", "1024x1024",
        lambda: ((r.randn(1024, 1024).astype("float32"),), {}))

    # --- attention (the north-star hot op) ---
    add("dot_product_attention", "B8_H12_L512_D64",
        lambda: ((r.randn(8, 12, 512, 64).astype("float32"),
                  r.randn(8, 12, 512, 64).astype("float32"),
                  r.randn(8, 12, 512, 64).astype("float32")), {}),
        4.0 * 8 * 12 * 512 * 512 * 64)
    add("dot_product_attention", "B4_H8_L2048_D64_causal_win256",
        lambda: ((r.randn(4, 8, 2048, 64).astype("float32"),
                  r.randn(4, 8, 2048, 64).astype("float32"),
                  r.randn(4, 8, 2048, 64).astype("float32")),
                 {"causal": True, "window": 256}),
        # useful FLOPs ~ 4*B*H*L*W*D inside the band
        4.0 * 4 * 8 * 2048 * 256 * 64)

    # --- patch extraction ---
    add("im2col", "B32_C64_HW56_K3",
        lambda: ((r.randn(32, 64, 56, 56).astype("float32"),),
                 {"kernel": (3, 3), "stride": (1, 1)}))

    # --- indexing ---
    add("take", "emb30k_1024x512",
        lambda: ((r.randn(30522, 256).astype("float32"),
                  r.randint(0, 30522, (1024,)).astype("int32")), {}))
    add("Embedding", "V30k_C256_B256xL64",
        lambda: ((r.randint(0, 30522, (256, 64)).astype("int32"),
                  r.randn(30522, 256).astype("float32")),
                 {"input_dim": 30522, "output_dim": 256}))

    # --- int8 path ---
    add("quantized_fully_connected", "B256_C1024_H1024_int8",
        lambda: ((r.randint(-127, 127, (256, 1024)).astype("int8"),
                  r.randint(-127, 127, (1024, 1024)).astype("int8"),
                  None,
                  onp.float32(-1), onp.float32(1),
                  onp.float32(-1), onp.float32(1)),
                 {"num_hidden": 1024, "no_bias": True}),
        2.0 * 256 * 1024 * 1024)
    return cfg


DEFAULT_SET = ["dot", "FullyConnected", "Convolution", "LayerNorm",
               "softmax", "dot_product_attention", "broadcast_add", "take"]


def bench_one(opname: str, case: str, builder: Callable,
              flops: Optional[float], iters: int = 10,
              with_bwd: bool = True) -> Dict:
    import jax
    import jax.numpy as jnp
    from incubator_mxnet_tpu.ops.registry import OPS

    fn = OPS[opname].fn
    args, kwargs = builder()
    dev_args = [None if a is None else jnp.asarray(a) for a in args]

    def fwd(*xs):
        out = fn(*xs, **kwargs)
        return out

    jfwd = jax.jit(fwd)

    def _sync(o):
        for leaf in jax.tree.leaves(o):
            leaf.block_until_ready()

    _sync(jfwd(*dev_args))  # compile
    t0 = time.perf_counter()
    for _ in range(iters):
        out = jfwd(*dev_args)
    _sync(out)
    fwd_ms = (time.perf_counter() - t0) / iters * 1e3

    row = {"op": opname, "case": case, "fwd_ms": round(fwd_ms, 4)}
    if flops:
        row["gflops"] = round(flops / (fwd_ms / 1e3) / 1e9, 1)

    if with_bwd:
        diff_idx = [i for i, a in enumerate(dev_args)
                    if a is not None
                    and jnp.issubdtype(a.dtype, jnp.floating)]
        if diff_idx:
            def loss(*xs):
                out = fn(*xs, **kwargs)
                leaves = [l for l in jax.tree.leaves(out)
                          if jnp.issubdtype(l.dtype, jnp.floating)]
                return sum(jnp.sum(l.astype(jnp.float32)) for l in leaves)

            try:
                jbwd = jax.jit(jax.grad(loss, argnums=tuple(diff_idx)))
                _sync(jbwd(*dev_args))
                t0 = time.perf_counter()
                for _ in range(iters):
                    g = jbwd(*dev_args)
                _sync(g)
                row["bwd_ms"] = round(
                    (time.perf_counter() - t0) / iters * 1e3, 4)
            except Exception:
                row["bwd_ms"] = None   # non-differentiable op
    return row


def run(ops: Optional[List[str]] = None, iters: int = 10,
        with_bwd: bool = True) -> List[Dict]:
    """Bench the named ops (default: the curated set). Every finished row
    is published as one ``opperf.result`` telemetry event, so a run with
    ``MXTPU_TELEMETRY_JSONL`` set leaves a stream
    ``tools/telemetry_check.py`` validates exactly like the serve bench's
    — machine consumers read the JSONL, not scraped stdout."""
    from incubator_mxnet_tpu import telemetry

    cfg = op_configs()
    names = ops if ops else DEFAULT_SET
    rows = []
    for name in names:
        if name not in cfg:
            rows.append({"op": name, "error": "no benchmark config"})
            telemetry.emit("opperf.result", severity="warning",
                           **rows[-1])
            continue
        for case, builder, flops in cfg[name]:
            try:
                rows.append(bench_one(name, case, builder, flops,
                                      iters=iters, with_bwd=with_bwd))
                telemetry.emit("opperf.result", **rows[-1])
            except Exception as e:  # pragma: no cover - per-op diagnostics
                rows.append({"op": name, "case": case,
                             "error": f"{type(e).__name__}: {e}"})
                telemetry.emit("opperf.result", severity="error",
                               **rows[-1])
    return rows


def run_performance_test(fn_name: str, inputs: dict, iters: int = 10) -> Dict:
    """Programmatic single-op entry (reference: opperf
    run_performance_test): ``inputs`` maps arg names to numpy arrays /
    values, applied positionally after sorting by key order given."""
    args = tuple(inputs.values())
    return bench_one(fn_name, "custom", lambda: (args, {}), None,
                     iters=iters)


def main(argv=None) -> int:
    # honor an explicit JAX_PLATFORMS over the TPU-tunnel plugin's
    # config override (it forces jax_platforms="axon,cpu" at boot)
    import os
    if os.environ.get("JAX_PLATFORMS"):
        import jax
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--ops", help="comma-separated op names")
    ap.add_argument("--all", action="store_true",
                    help="every op with a config")
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--no-bwd", action="store_true")
    ap.add_argument("--json", help="write the report to this file")
    args = ap.parse_args(argv)
    names = None
    if args.all:
        names = sorted(op_configs())
    elif args.ops:
        names = [s.strip() for s in args.ops.split(",") if s.strip()]
    rows = run(names, iters=args.iters, with_bwd=not args.no_bwd)
    import jax
    from incubator_mxnet_tpu import telemetry
    report = {"backend": jax.default_backend(),
              "device": str(jax.devices()[0].device_kind),
              "rows": rows}
    # the summary rides the telemetry stream too (per-row events were
    # emitted by run()); stdout keeps the one strict-JSON report line
    telemetry.emit("opperf.report", backend=report["backend"],
                   device=report["device"], rows=len(rows),
                   errors=sum(1 for r in rows if "error" in r))
    text = telemetry.dumps_strict(report, indent=2)
    if args.json:
        with open(args.json, "w") as f:
            f.write(text)
    print(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())

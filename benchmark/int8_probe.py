#!/usr/bin/env python
"""int8-on-MXU evidence probe (VERDICT r3 weak #8 / next #9).

Measures a quantized Dense layer vs its bf16 original on the live device
and inspects the compiled HLO for signs that the int8 dot actually lowered
to integer MXU ops (vs dequantizing early to a float dot).

Whole-forward timing only — per-op microbenches through the tunnel are
dispatch-dominated (BASELINE.md measurement caveat), so we amortize over a
large batch and many iterations and sync once.

Prints ONE JSON line with keys: int8_ms, bf16_ms, speedup,
hlo_has_int8_dot, hlo_convert_before_dot, backend.

Reference counterpart: src/operator/quantization/ op suite + the perf FAQ's
quantization section (SURVEY §2.4); here the evidence target is the MXU's
int8 path via XLA.
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as onp

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:  # `python benchmark/int8_probe.py` direct run
    sys.path.insert(0, REPO)


def main() -> None:
    import jax

    # The axon plugin forces jax_platforms='axon,cpu' at interpreter boot,
    # so the JAX_PLATFORMS env var alone cannot pin this probe to CPU for
    # smoke runs — honor it in-process (unset → default device, the TPU).
    # CAVEAT: jax.config.update('jax_platforms', ...) is a silent no-op
    # once the backends are initialized — the probe would then run (and
    # report timings) on whatever platform the first device lookup chose.
    # Check the bridge state and refuse to pretend the pin worked.
    if os.environ.get("JAX_PLATFORMS"):
        requested = os.environ["JAX_PLATFORMS"]
        from jax._src import xla_bridge as _bridge
        initialized = getattr(_bridge, "backends_are_initialized",
                              lambda: bool(getattr(_bridge, "_backends",
                                                   None)))()
        if initialized:
            actual = jax.default_backend()
            if actual not in requested.split(","):
                import sys
                print(f"[int8_probe] JAX_PLATFORMS={requested!r} requested "
                      f"but the XLA backends are already initialized "
                      f"(active: {actual!r}) — jax.config.update("
                      f"'jax_platforms') is a no-op at this point and the "
                      f"probe would silently time the wrong platform. Run "
                      f"this probe in a fresh interpreter with the env var "
                      f"set at launch.", file=sys.stderr)
                raise SystemExit(2)
        else:
            jax.config.update("jax_platforms", requested)
    import jax.numpy as jnp

    B, IN, OUT = (int(os.environ.get(k, d)) for k, d in
                  (("MXTPU_INT8_BATCH", "4096"), ("MXTPU_INT8_IN", "4096"),
                   ("MXTPU_INT8_OUT", "4096")))
    iters = int(os.environ.get("MXTPU_INT8_ITERS", "30"))

    rng = onp.random.RandomState(0)
    w8 = rng.randint(-127, 128, (OUT, IN)).astype(onp.int8)
    x8 = rng.randint(-127, 128, (B, IN)).astype(onp.int8)
    xbf = jnp.asarray(rng.randn(B, IN), jnp.bfloat16)
    wbf = jnp.asarray(rng.randn(OUT, IN), jnp.bfloat16)
    sx, sw = 0.017, 0.021  # activation/weight scales (values irrelevant)

    @jax.jit
    def int8_dense(x, w):
        # the quantized-Dense inner contraction: int8 x int8 -> int32
        # accumulate on the MXU, one scale multiply after
        acc = jax.lax.dot_general(x, w, (((1,), (1,)), ((), ())),
                                  preferred_element_type=jnp.int32)
        return acc.astype(jnp.float32) * (sx * sw)

    @jax.jit
    def bf16_dense(x, w):
        return jax.lax.dot_general(x, w, (((1,), (1,)), ((), ())),
                                   preferred_element_type=jnp.float32)

    xi, wi = jnp.asarray(x8), jnp.asarray(w8)
    hlo = int8_dense.lower(xi, wi).compile().as_text()
    # Post-optimization HLO: an integer MXU dot shows up as a dot/fusion
    # producing s32 (or convolution with s8 operands); a float line with no
    # s32 producer anywhere means the compiler dequantized early.
    import re
    int_dots = re.findall(r"s32\[[^\]]*\][^\n]*(?:dot|fusion|custom-call)",
                          hlo)
    has_int8_dot = bool(int_dots) and "s8[" in hlo
    early_convert = not has_int8_dot

    def _time(fn, *args):
        fn(*args).block_until_ready()
        onp.asarray(fn(*args))          # honest tunnel sync
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(*args)
        onp.asarray(out)
        return (time.perf_counter() - t0) / iters * 1e3

    int8_ms = _time(int8_dense, xi, wi)
    bf16_ms = _time(bf16_dense, xbf, wbf)

    # The synthetic dense above proves the MXU path exists; the bucket
    # census below proves the SERVED graphs actually take it. Trace the
    # quantized-zoo twin (models.quantized_smoke — the same entry
    # mxlint --hlo --quantized lints and serve_bench --int8 runs) and
    # report the per-bucket int8 census from the MX71x pass's own
    # boundary accounting, so the probe's evidence and the lint's
    # verdict can never disagree.
    family = os.environ.get("MXTPU_INT8_FAMILY", "lenet")
    from incubator_mxnet_tpu import analysis, models
    qsm = models.quantized_smoke(family)
    traced = analysis.hlo.trace_entry(
        qsm["compiled"], max_graphs=max(8, qsm["table"].num_buckets()))
    buckets = []
    for g in traced.graphs:
        st = analysis.hlo.quant_graph_stats(g)
        buckets.append({
            "site": g.site,
            "signature": [list(s) for s in (g.signature or [])],
            "quantized": st.quantized,
            "int8_matmuls": len(st.int_matmuls),
            "quantize_boundaries": len(st.q_converts),
            "dequantize_boundaries": len(st.dq_converts),
            "saved_bytes": st.saved_bytes,
            "churn_bytes": st.churn_bytes,
        })

    print(json.dumps({
        "metric": "int8_dense_vs_bf16",
        "int8_ms": round(int8_ms, 4), "bf16_ms": round(bf16_ms, 4),
        "speedup": round(bf16_ms / int8_ms, 3),
        "hlo_has_int8_dot": bool(has_int8_dot),
        "hlo_convert_before_dot": bool(early_convert),
        "shape": [B, IN, OUT],
        "quantized_zoo": {
            "family": family,
            "buckets": buckets,
            "all_buckets_quantized": bool(buckets) and all(
                b["quantized"] for b in buckets),
        },
        "backend": jax.default_backend(),
    }))


if __name__ == "__main__":
    main()

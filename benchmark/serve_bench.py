#!/usr/bin/env python
"""serve_bench — offline throughput/latency sweep + dynamic-batching demo.

The serving counterpart of ``bench.py`` (which measures training steps):
one command produces a BENCH-style JSON record covering

1. **offline sweep**: for each batch-bucket size, steady-state
   ``CompiledModel.predict`` latency and throughput (rows/sec) — the
   padded-batch replay ceiling;
2. **dynamic section** (the ISSUE acceptance demo): N mixed-shape single
   requests pushed through a :class:`DynamicBatcher` from client threads —
   p50/p95/p99 end-to-end latency, throughput, batch occupancy, queue
   high-water, and the compile-cache counters with **zero post-warmup
   recompiles asserted** (rc != 0 on violation);
3. per-stage wall time from the profiler span recorder
   (pad / compute / unpad / batch), a ``serve.predict`` host-gap
   attribution (``profiler.step_report``), and a device-blind perf-proxy
   record (``analysis.hlo.cost`` FLOPs/bytes/fusion per bucket graph —
   the serving sibling of ``bench.py --proxy``), also emitted as one
   ``perf.proxy`` telemetry event.

Usage::

    python -m benchmark.serve_bench --smoke          # <60 s CPU CI config
    python -m benchmark.serve_bench --model bert --requests 5000
    python -m benchmark.serve_bench --replicas 3     # HA tier in front
    python -m benchmark.serve_bench --smoke --chaos-replicas  # restart drill
    python -m benchmark.serve_bench --smoke --decode  # autoregressive serving
    python -m benchmark.serve_bench --out serve_bench.json

``--decode`` swaps in the autoregressive serving section (``serve.decode``):
ragged prompts stream through the paged-KV-cache continuous-batching stack
and the record reports tokens/sec, ITL p50/p99, TTFT, step occupancy, the
statically priced capacity, and the goodput serve twin — gated device-blind
on zero post-warmup recompiles across ragged generation lengths, MX706/MX709
clean over the decode graphs, and static capacity == the runtime block
pool's admission limit.

``--replicas N`` runs the dynamic section through the HA serve tier —
N :class:`Replica` workers prewarmed from a shared on-disk artifact
cache behind the health-checked failover :class:`Router` — and records
failover-path p99 latency and the shed rate. ``--chaos-replicas`` is the
restart drill (seeded ``replica_kill`` + ``corrupt_artifact`` mid-run),
gated on zero silent drops, full replica recovery, zero steady-state
compiles on the process-wide ledger, the prewarm-from-cache contract
(restarts load verified artifacts — exactly one cold miss plus the one
injected corruption across the whole run), and (under
``MXTPU_LOCKCHECK=1``) zero lock-order inversions.

Env: ``MXTPU_SERVE_BENCH_MODEL`` (mlp|lenet|bert), ``MXTPU_SERVE_BENCH_N``
(request count) mirror the flags for harness use.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=1")
import jax  # noqa: E402

import numpy as onp  # noqa: E402


def _build(model_name: str, smoke: bool):
    """Returns (net, table, spec, make_request(rng) -> per-example args)."""
    from incubator_mxnet_tpu import models, nd, serve

    if model_name == "bert":
        vocab, max_len = 1000, 64 if smoke else 128
        net = models.get_bert("bert_2_128_2", vocab_size=vocab,
                              max_length=max_len, dropout=0.1,
                              use_decoder=False, use_classifier=False)
        net.initialize()
        net.hybridize()
        rng = onp.random.RandomState(0)
        L = 16
        ids = nd.array(rng.randint(1, vocab, (2, L)).astype("int32"))
        tt = nd.array(onp.zeros((2, L), "int32"))
        vl = nd.array(onp.full((2,), L, "float32"))
        net(ids, tt, vl)
        table = serve.BucketTable({"batch": (1, 8 if smoke else 32),
                                   "seq": (8, 32 if smoke else max_len)})
        spec = models.serve_spec("bert_encoder")

        def make_request(rng):
            L = int(rng.randint(4, (32 if smoke else max_len) - 1))
            return (rng.randint(1, vocab, (L,)).astype("int32"),
                    onp.zeros((L,), "int32"), onp.float32(L))

        return net, table, spec, make_request

    if model_name == "lenet":
        net = models.LeNet()
        net.initialize()
        net.hybridize()
        from incubator_mxnet_tpu import nd
        x = nd.array(onp.zeros((2, 1, 28, 28), "float32"))
        net(x)
        table = serve.BucketTable({"batch": (1, 16 if smoke else 64)})
        spec = models.serve_spec("lenet")

        def make_request(rng):
            return (rng.randn(1, 28, 28).astype("float32"),)

        return net, table, spec, make_request

    # mlp: the fastest smoke model
    from incubator_mxnet_tpu import gluon, nd
    net = gluon.nn.HybridSequential(prefix="servebench_")
    with net.name_scope():
        net.add(gluon.nn.Dense(64, activation="relu", in_units=32))
        net.add(gluon.nn.Dense(8, in_units=64))
    net.initialize()
    net.hybridize()
    net(nd.array(onp.zeros((2, 32), "float32")))
    table = serve.BucketTable({"batch": (1, 16 if smoke else 64)})
    spec = {"input_axes": [{0: "batch"}], "output_axes": [{0: "batch"}],
            "pad_values": [0]}

    def make_request(rng):
        return (rng.randn(32).astype("float32"),)

    return net, table, spec, make_request


def offline_sweep(model, table, make_request, iters: int):
    """Steady-state padded-batch latency per batch bucket."""
    from incubator_mxnet_tpu.serve.batcher import stack_examples

    rows = []
    rng = onp.random.RandomState(1)
    axis = model._primary_axis
    for bucket in table.sizes(axis):
        reqs = [make_request(rng) for _ in range(bucket)]
        # mixed per-request lengths (bert): pad to the batch max exactly
        # like a batcher flush would
        stacked = stack_examples(model, reqs)
        model.predict(*stacked)  # steady-state: bucket already warmed
        t0 = time.perf_counter()
        for _ in range(iters):
            out = model.predict(*stacked)
        out = out[0] if isinstance(out, tuple) else out
        out.asnumpy()  # sync
        dt = (time.perf_counter() - t0) / iters
        rows.append({"batch": bucket, "latency_ms": round(dt * 1e3, 3),
                     "rows_per_sec": round(bucket / dt, 1)})
    return rows


def replicated_run(net, table, spec, make_request, n_requests: int,
                   clients: int, deadline_ms: float, n_replicas: int,
                   chaos: bool, cache_root: str, chaos_seed: int = 23):
    """Dynamic section behind the HA tier: N replicas prewarmed from one
    shared artifact cache, a health-checked failover Router in front.

    ``chaos=True`` is the restart drill: once ~25% of the traffic is in,
    a seeded ``replica_kill`` (one replica dies mid-request) and one
    ``corrupt_artifact`` (the restart's cache read is bit-flipped on
    disk) are armed. Gates, asserted by the caller from the returned
    record: zero silent drops (every accepted request completes or is
    explicitly shed with ``retry_after``), the killed replica rejoins
    healthy, and the compile ledger stays at zero post-warmup compiles.
    """
    from incubator_mxnet_tpu import serve
    from incubator_mxnet_tpu.fault import inject
    from incubator_mxnet_tpu.util import nearest_rank_percentile

    cache = serve.ArtifactCache(cache_root)
    # each client issues n//clients requests; account against what was
    # actually ISSUED or the silent-drop gate false-positives whenever
    # n_requests is not divisible by clients
    issued = (n_requests // clients) * clients
    input_names = [f"d{i}" for i in range(len(spec["input_axes"]))]

    def loader(rep):
        rep.load("bench", table=table, input_axes=spec["input_axes"],
                 factory=lambda: net, cache=cache,
                 input_names=input_names,
                 output_axes=spec["output_axes"],
                 pad_values=spec["pad_values"])

    replicas = [serve.Replica(f"r{i}", loader, max_delay_ms=deadline_ms)
                for i in range(n_replicas)]
    router = serve.Router(replicas, heartbeat_ms=50,
                          retries=max(3, n_replicas)).start()

    lock = threading.Lock()
    lat_ms, failover_lat_ms, shed_after, errors = [], [], [], []
    trace_ids = []
    progress = {"done": 0}

    def client(cid: int):
        rng = onp.random.RandomState(100 + cid)
        for _ in range(n_requests // clients):
            try:
                _, info = router.call_detailed(
                    "bench", *make_request(rng), tenant=f"tenant{cid % 2}")
                with lock:
                    lat_ms.append(info["latency_ms"])
                    # unsampled traces record no spans by design —
                    # only sampled ids enter the rooted-tree gate (at
                    # the default 0.1 rate ~90% of requests would
                    # otherwise read as "missing" stitching failures)
                    if info.get("trace_sampled"):
                        trace_ids.append(info.get("trace_id"))
                    if info["failovers"] or info["retries"]:
                        failover_lat_ms.append(info["latency_ms"])
            except (serve.ShedError, serve.DeadlineExceeded) as e:
                with lock:  # explicit rejection WITH a backoff hint —
                    shed_after.append(e.retry_after)  # never a silent drop
            except Exception as e:  # noqa: BLE001 — gate evidence
                with lock:
                    errors.append(f"{type(e).__name__}: {e}")
            with lock:
                progress["done"] += 1

    t0 = time.perf_counter()
    threads = [threading.Thread(target=client, args=(c,),
                                name=f"bench-client-{c}", daemon=False)
               for c in range(clients)]
    for t in threads:
        t.start()
    chaos_at = None
    if chaos:
        arm_at = issued // 4
        while True:
            with lock:
                if progress["done"] >= arm_at or errors:
                    break
            time.sleep(0.002)
        inject.enable(seed=chaos_seed,
                      crash_sites=["replica_kill", "corrupt_artifact"])
        chaos_at = time.perf_counter()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0

    # recovery: every replica (incl. the killed one) back to healthy —
    # states snapshot BEFORE stop(), which winds the tier down to stopped
    recovery_s = None
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        final_states = router.replicas.states()
        if all(s == "healthy" for s in final_states.values()):
            if chaos_at is not None:
                recovery_s = round(time.perf_counter() - chaos_at, 3)
            break
        time.sleep(0.05)
    if chaos:
        inject.disable()
    snap = router.snapshot()
    router.stop()
    ok = len(lat_ms)
    lat_sorted = sorted(lat_ms)
    fo_sorted = sorted(failover_lat_ms)
    return {
        "replicas": n_replicas,
        "requests": issued,
        "ok": ok,
        "shed": len(shed_after),
        "shed_rate": round(len(shed_after) / issued, 4) if issued else 0.0,
        "errors": errors[:5],
        "silent_drops": issued - ok - len(shed_after) - len(errors),
        "wall_s": round(wall, 3),
        "throughput_req_per_sec": round(ok / wall, 1) if wall else 0.0,
        "latency_ms_p50": round(nearest_rank_percentile(lat_sorted, 50), 3)
        if lat_sorted else None,
        "latency_ms_p99": round(nearest_rank_percentile(lat_sorted, 99), 3)
        if lat_sorted else None,
        "failover_latency_ms_p99":
            round(nearest_rank_percentile(fo_sorted, 99), 3)
            if fo_sorted else None,
        "failover_requests": len(failover_lat_ms),
        "chaos": chaos,
        "recovery_s": recovery_s,
        "replica_states": final_states,
        "router": snap["stats"],
        "prewarm_cache": cache.snapshot(),
        "tracing": _trace_stitching(trace_ids),
    }


def _trace_stitching(trace_ids):
    """The rooted-tree gate over every completed request's trace: each
    sampled trace must stitch into EXACTLY one rooted tree (a hedged or
    failover request is siblings under one parent, not a forest), and
    the whole ring must hold zero orphan spans — the trace-smoke CI
    contract."""
    from incubator_mxnet_tpu.telemetry import trace as _trace

    sampled = [t for t in trace_ids if t]
    rooted = forests = missing = 0
    for tid in sampled:
        t = _trace.tree(tid)
        if t is None:
            missing += 1
        elif t["span"].get("name") == "<forest>":
            forests += 1
        else:
            rooted += 1
    return {
        "sample_rate": _trace.sample_rate(),
        "requests_traced": len(sampled),
        "rooted_trees": rooted,
        "forests": forests,
        "missing": missing,
        "orphan_spans": len(_trace.orphans()),
        "ring_spans": len(_trace.spans()),
    }


def tracing_overhead(model, make_request, iters: int):
    """A/B the tracing tax on the hot predict path: p50 per-request
    latency with head sampling at the default rate vs tracing disabled
    (rate 0: contexts propagate, nothing records). At the default rate
    most probes draw unsampled, so the gated p50 bounds the ALWAYS-ON
    tax every request pays (sampling decision, context propagation) —
    exactly the "tracing at default config" cost the acceptance
    criterion names. A third arm at rate 1.0 reports the fully-sampled
    recording path (span rings, adopted profiler sub-spans) as
    ``overhead_pct_sampled``, informational only. Interleaved probes so
    clock drift and cache state cancel, and the best of 5 rounds is
    gated: a real per-request tax shows up in EVERY round, while a noisy
    CI neighbour only inflates some — min-of-rounds keeps the 3% budget
    meaningful on a shared 2-core runner. The acceptance gate is p50
    regression < 3% at the default rate."""
    from incubator_mxnet_tpu.serve.batcher import stack_examples
    from incubator_mxnet_tpu.telemetry import trace as _trace
    from incubator_mxnet_tpu.util import nearest_rank_percentile

    rng = onp.random.RandomState(7)
    stacked = stack_examples(model, [make_request(rng)])
    default_rate = _trace.sample_rate()

    def probe(rate):
        _trace.set_sample_rate(rate)
        try:
            # the timed window covers the root span's own open/finish —
            # id generation and the ring append are per-request costs
            # every real sampled request pays, so the gate must count
            # them
            t0 = time.perf_counter()
            with _trace.span("bench.request"):
                model.predict(*stacked)
            return (time.perf_counter() - t0) * 1e3
        finally:
            _trace.set_sample_rate(None)

    probe(default_rate), probe(0.0), probe(1.0)  # warm all paths
    rounds, full_rounds = [], []
    for _ in range(5):
        on_ms, off_ms, full_ms = [], [], []
        # the GATED pair is a pure on/off interleave — inserting the
        # recording-heavy rate-1.0 probe between them measurably taxes
        # the adjacent on-probe (allocator/cache pollution) and inflates
        # the gated delta with cost the default-rate path never pays
        for _ in range(iters):
            on_ms.append(probe(default_rate))
            off_ms.append(probe(0.0))
        for _ in range(iters):
            full_ms.append(probe(1.0))
        p50_on = nearest_rank_percentile(sorted(on_ms), 50)
        p50_off = nearest_rank_percentile(sorted(off_ms), 50)
        p50_full = nearest_rank_percentile(sorted(full_ms), 50)
        rounds.append((((p50_on - p50_off) / p50_off if p50_off else 0.0),
                       p50_on, p50_off))
        full_rounds.append((p50_full - p50_off) / p50_off if p50_off
                           else 0.0)
    overhead, p50_on, p50_off = min(rounds)
    return {"sample_rate": default_rate, "iters": iters,
            "rounds": len(rounds),
            "p50_ms_sampled": round(p50_on, 4),
            "p50_ms_disabled": round(p50_off, 4),
            "overhead_pct": round(overhead * 100, 2),
            "overhead_pct_rounds": [round(r[0] * 100, 2) for r in rounds],
            # recording-path tax at rate 1.0 — informational, not gated
            "overhead_pct_sampled": round(min(full_rounds) * 100, 2),
            "budget_pct": 3.0,
            "pass": bool(overhead < 0.03)}


def dynamic_run(model, spec, make_request, n_requests: int,
                clients: int, deadline_ms: float):
    from incubator_mxnet_tpu import serve

    batcher = serve.DynamicBatcher(model, max_delay_ms=deadline_ms).start()
    errors = []
    lock = threading.Lock()

    def client(cid: int):
        rng = onp.random.RandomState(100 + cid)
        n = n_requests // clients
        for _ in range(n):
            try:
                fut = batcher.submit(*make_request(rng))
                fut.result(timeout=120)
            except Exception as e:  # noqa: BLE001 — collected for the report
                with lock:
                    errors.append(f"{type(e).__name__}: {e}")
                return

    t0 = time.perf_counter()
    threads = [threading.Thread(target=client, args=(c,),
                                name=f"bench-client-{c}", daemon=False)
               for c in range(clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    snap = batcher.metrics.snapshot(model)
    batcher.stop()
    served = snap["requests"]
    return {
        "requests": served,
        "wall_s": round(wall, 3),
        "throughput_req_per_sec": round(served / wall, 1) if wall else 0.0,
        "clients": clients,
        "deadline_ms": deadline_ms,
        "errors": errors[:5],
        **{k: snap[k] for k in ("latency", "batch_latency",
                                "batch_occupancy", "queue_max_depth",
                                "batches", "rejected")},
        "compile_cache": snap["compile_cache"],
    }


def decode_run(n_requests: int, smoke: bool, out_path=None) -> int:
    """The ``--decode`` section: autoregressive serving through the paged
    KV-cache + continuous batching stack (``serve.decode``), gated
    device-blind on the ISSUE's acceptance criteria:

    1. **zero post-warmup recompiles** across ragged generation lengths —
       the process-wide compile ledger's warm contract
       (``compile_log.assert_zero_post_warmup``), not a per-model counter;
    2. **MX706/MX709 clean** over every decode-engine graph (the bucketed
       prefill ladder AND the AOT single-token step) via the
       ``analysis.hlo`` staging lint;
    3. the **static capacity** number the liveness model priced equals
       the runtime block pool's actual admission limit, and re-pricing is
       deterministic (same inputs → the same number).

    Measured alongside: tokens/sec, ITL p50/p99, TTFT, step occupancy,
    and the goodput serve twin (prefill-bound vs decode-bound wall split,
    measured tokens/sec vs the per-token roofline ceiling).
    """
    from incubator_mxnet_tpu import nd, serve
    from incubator_mxnet_tpu.analysis import hlo as _hlo
    from incubator_mxnet_tpu.models.nmt import NMTModel
    from incubator_mxnet_tpu.telemetry import compile_log
    from incubator_mxnet_tpu.telemetry import goodput as _goodput

    rng = onp.random.RandomState(0)
    if smoke:
        dims = dict(units=32, hidden_size=64, num_layers=2, num_heads=2)
        vocab, max_src, max_tgt, max_batch = 31, 16, 24, 4
    else:
        dims = dict(units=128, hidden_size=256, num_layers=4, num_heads=4)
        vocab, max_src, max_tgt, max_batch = 512, 64, 64, 8
    model = NMTModel(src_vocab=vocab, tgt_vocab=vocab, dropout=0.0,
                     max_length=max(max_src, max_tgt), prefix="decbench_",
                     **dims)
    model.initialize()
    src = nd.array(rng.randint(3, vocab, (2, 6)).astype("int32"))
    tgt = nd.array(rng.randint(3, vocab, (2, 5)).astype("int32"))
    model(src, tgt)  # materialise params

    table = serve.BucketTable({"batch": (1, 1), "src": (4, max_src)})
    engine = serve.DecodeEngine(model, table, max_batch=max_batch,
                                block_size=4, max_target_len=max_tgt,
                                hbm_budget=1 << 26)

    # gate 2 — staging lint over the decode entry (prefill ladder + AOT
    # step), trace-only, before the first compile
    analysis_rep = _hlo.verify(engine,
                               max_graphs=max(8, table.num_buckets() + 1))
    if analysis_rep.errors:
        print("serve_bench --decode: analysis.hlo found "
              f"{len(analysis_rep.errors)} error-severity finding(s): "
              f"{[d.code for d in analysis_rep.errors]}", file=sys.stderr)
        return 1

    # gate 3 — capacity: static number == runtime admission limit, and
    # re-pricing from the same inputs reproduces it exactly
    capacity = dict(engine.capacity)
    repriced = engine.capacity_report()
    if repriced != engine.capacity:
        print(f"serve_bench --decode: CAPACITY NOT DETERMINISTIC: "
              f"{engine.capacity} re-priced as {repriced}", file=sys.stderr)
        return 1
    if capacity["max_sequences"] != engine.pool.admission_limit():
        print("serve_bench --decode: STATIC CAPACITY MISMATCH: priced "
              f"{capacity['max_sequences']} sequences but the pool admits "
              f"{engine.pool.admission_limit()}", file=sys.stderr)
        return 1

    # goodput serve twin: per-token roofline ceiling from the same
    # device-blind cost model, decode-step FLOPs per generated token
    _goodput.configure(on=True)
    _goodput.begin(reset_totals=True)
    cost_rep = _hlo.cost(engine, max_graphs=max(8, table.num_buckets() + 1))
    step_rows = [r for r in cost_rep.rows
                 if "step" in (r.entry or "").lower()]
    step_flops = (step_rows[-1].flops if step_rows
                  else cost_rep.model_flops_per_step())
    _goodput.set_serve_cost_profile(
        flops_per_token=step_flops / max_batch,
        source="analysis.hlo.cost(DecodeEngine.step)")

    t_warm = time.perf_counter()
    engine.warmup()
    warm_ms = round((time.perf_counter() - t_warm) * 1e3, 1)
    warm_compiles = len(compile_log.records())

    batcher = serve.DecodeBatcher(engine).start()
    streams, errors = [], []
    try:
        # ragged on BOTH axes — prompt lengths span the prefill buckets,
        # generation lengths exercise block-boundary growth and
        # token-boundary join/leave — so the warm contract is asserted
        # across the shapes continuous batching actually sees
        for i in range(n_requests):
            ls = int(rng.randint(2, max_src))
            prompt = rng.randint(3, vocab, (ls,)).astype("int32")
            streams.append(batcher.submit(
                prompt, max_new_tokens=int(rng.randint(1, max_tgt - 1)),
                tenant=f"tenant{i % 2}"))
        t0 = time.perf_counter()
        for s in streams:
            try:
                s.result(timeout=120)
            except Exception as e:  # noqa: BLE001 — gate evidence
                errors.append(f"{type(e).__name__}: {e}")
        wall = time.perf_counter() - t0
    finally:
        batcher.stop()
    if errors:
        print(f"serve_bench --decode: {len(errors)} stream error(s): "
              f"{errors[:5]}", file=sys.stderr)
        return 1

    # gate 1 — the warm contract on the process-wide ledger: every
    # compile so far was warmup-phase, none after
    try:
        compile_log.assert_zero_post_warmup()
    except Exception as e:  # noqa: BLE001 — the gate's evidence
        print("serve_bench --decode: ZERO-RECOMPILE CONTRACT VIOLATED "
              f"across ragged generation lengths: {e}", file=sys.stderr)
        return 1

    snap = batcher.metrics.snapshot()
    serve_goodput = _goodput.serve_report()
    _goodput.configure()  # drop the programmatic override
    tokens = snap["tokens"]
    result = {
        "metric": "serve_decode_tokens_per_sec",
        "value": round(tokens / wall, 1) if wall else 0.0,
        "unit": "tokens/sec",
        "vs_baseline": None,
        "extra": {
            "backend": jax.default_backend(),
            "requests": n_requests,
            "tokens": tokens,
            "wall_s": round(wall, 3),
            "itl_ms_p50": snap["itl"].get("itl_ms_p50"),
            "itl_ms_p99": snap["itl"].get("itl_ms_p99"),
            "ttft_ms_p50": snap["ttft"].get("ttft_ms_p50"),
            "step_occupancy": snap["step_occupancy"],
            "capacity": capacity,
            "admission_limit": engine.pool.admission_limit(),
            "pool": engine.pool.snapshot(),
            "warmup_ms": warm_ms,
            "warmup_compiles": warm_compiles,
            "post_warmup_compiles": compile_log.post_warmup_compiles(),
            "analysis": analysis_rep.summary_dict(),
            "goodput_serve": serve_goodput,
            "decode_metrics": snap,
        },
    }
    doc = json.dumps(result)
    print(doc)
    if out_path:
        with open(out_path, "w") as f:
            f.write(doc + "\n")
    return 0


def int8_run(model_name: str, n_requests: int, clients: int,
             deadline_ms: float, iters: int, out_path=None) -> int:
    """The ``--int8`` section: calibrated int8 serving through the
    quantized zoo (``models.quantized_smoke`` — the same entry
    ``mxlint --hlo --quantized`` lints and the autotune ``quantize``
    dimension prices), gated device-blind:

    1. **MX71x staging lint** over every quantized bucket graph
       (``analysis.hlo.verify(..., quant=True)`` — the gate
       ``ModelRegistry`` applies): a silent f32 promotion (MX711),
       missing calibration (MX712), or q/dq hazard (MX713) fails in
       seconds, before the first compile;
    2. **MX709 ladder feasibility at HALF the f32 budget**: the int8
       twin's whole-ladder residency must fit a budget set to half the
       float model's own ladder peak — the "int8 buys you double the
       geometry" claim as a hard lint gate;
    3. **zero post-warmup recompiles** across the mixed-shape dynamic
       workload — quantized buckets AOT-warm exactly like float ones;
    4. the banked int8 proxy (bytes/step, peak residency) must come in
       strictly below the f32 twin's — the record carries both and
       their ratio.
    """
    from incubator_mxnet_tpu import models, serve
    from incubator_mxnet_tpu.analysis import hlo as _hlo

    family = "bert_encoder" if model_name == "bert" else "lenet"
    qsm = models.quantized_smoke(family)
    qcm, table, spec = qsm["compiled"], qsm["table"], qsm["spec"]
    f32 = qsm["f32"]["compiled"]
    max_g = max(8, table.num_buckets())

    def make_request(rng):
        if family == "lenet":
            return (rng.randn(1, 28, 28).astype("float32"),)
        L = int(rng.randint(4, table.axes["seq"][1]))
        return (rng.randint(1, 1000, (L,)).astype("int32"),
                onp.zeros((L,), "int32"), onp.float32(L))

    # gate 1 — the MX71x staging lint (same call ModelRegistry stages
    # with), trace-only, before any compile
    analysis_rep = _hlo.verify(qcm, max_graphs=max_g, quant=True)
    if analysis_rep.errors:
        print("serve_bench --int8: analysis.hlo rejected the quantized "
              f"model: {[d.code for d in analysis_rep.errors]}",
              file=sys.stderr)
        return 1

    # gate 2 + 4 — price both twins device-blind, then re-lint the int8
    # ladder against HALF the float ladder's own residency
    cost_q = _hlo.cost(qcm, max_graphs=max_g)
    cost_f = _hlo.cost(f32, max_graphs=max_g)
    f32_ladder = cost_f.ladder_peak_bytes()
    half_budget = f32_ladder // 2
    half_rep = _hlo.verify(qcm, max_graphs=max_g,
                           hbm_budget_bytes=half_budget)
    mx709 = [d for d in half_rep.diagnostics if d.code == "MX709"]
    if mx709:
        print("serve_bench --int8: INT8 LADDER INFEASIBLE AT HALF THE "
              f"F32 BUDGET ({half_budget} bytes): "
              f"{[d.message for d in mx709]}", file=sys.stderr)
        return 1
    bytes_ratio = (cost_q.bytes_per_step() / cost_f.bytes_per_step()
                   if cost_f.bytes_per_step() else None)
    peak_ratio = (cost_q.ladder_peak_bytes() / f32_ladder
                  if f32_ladder else None)
    if bytes_ratio is None or bytes_ratio >= 1.0:
        print("serve_bench --int8: quantized bytes/step "
              f"({cost_q.bytes_per_step()}) is not below the f32 twin "
              f"({cost_f.bytes_per_step()})", file=sys.stderr)
        return 1

    # gate 3 — warm every quantized bucket, then the mixed-shape
    # dynamic workload must add zero compiles
    warm = qcm.warmup()
    sweep = offline_sweep(qcm, table, make_request, iters)
    dyn = dynamic_run(qcm, spec, make_request, n_requests, clients,
                      deadline_ms)
    if dyn["errors"]:
        print(f"serve_bench --int8: {len(dyn['errors'])} client "
              f"error(s): {dyn['errors']}", file=sys.stderr)
        return 1
    recompiles = dyn["compile_cache"]["post_warmup_compiles"]
    if recompiles:
        print("serve_bench --int8: ZERO-RECOMPILE CONTRACT VIOLATED: "
              f"{recompiles} post-warmup compile(s) on the quantized "
              "buckets", file=sys.stderr)
        return 1

    result = {
        "metric": f"serve_int8_{family}_throughput_req_per_sec",
        "value": dyn["throughput_req_per_sec"],
        "unit": "req/sec",
        "vs_baseline": None,
        "extra": {
            "family": family,
            "backend": jax.default_backend(),
            "warmup": warm,
            "offline_sweep": sweep,
            "dynamic": dyn,
            "analysis": analysis_rep.summary_dict(),
            "proxy_int8": {
                "bytes_per_step": cost_q.bytes_per_step(),
                "peak_live_bytes": cost_q.peak_live_bytes(),
                "ladder_peak_bytes": cost_q.ladder_peak_bytes(),
            },
            "proxy_f32": {
                "bytes_per_step": cost_f.bytes_per_step(),
                "peak_live_bytes": cost_f.peak_live_bytes(),
                "ladder_peak_bytes": f32_ladder,
            },
            "bytes_ratio_vs_f32": round(bytes_ratio, 4),
            "ladder_peak_ratio_vs_f32": (round(peak_ratio, 4)
                                         if peak_ratio is not None
                                         else None),
            "half_f32_budget_bytes": half_budget,
            "mx709_at_half_budget": len(mx709),
        },
    }
    doc = json.dumps(result)
    print(doc)
    if out_path:
        with open(out_path, "w") as f:
            f.write(doc + "\n")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--model", default=os.environ.get(
        "MXTPU_SERVE_BENCH_MODEL", "mlp"), choices=["mlp", "lenet", "bert"])
    ap.add_argument("--requests", type=int, default=int(os.environ.get(
        "MXTPU_SERVE_BENCH_N", "1000")))
    ap.add_argument("--clients", type=int, default=4)
    ap.add_argument("--iters", type=int, default=20,
                    help="offline timed iterations per bucket")
    ap.add_argument("--deadline-ms", type=float, default=None)
    ap.add_argument("--smoke", action="store_true",
                    help="<60s CPU config: small buckets, fewer iters")
    ap.add_argument("--replicas", type=int, default=0,
                    help="N>0: run the dynamic section through the HA "
                    "tier (N replicas prewarmed from a shared artifact "
                    "cache behind the failover Router)")
    ap.add_argument("--chaos-replicas", action="store_true",
                    help="the replica restart drill: seeded replica_kill "
                    "+ corrupt_artifact mid-run, gated on zero silent "
                    "drops, full recovery, and zero post-warmup compiles "
                    "(implies --replicas 3)")
    ap.add_argument("--decode", action="store_true",
                    help="run the autoregressive decode section instead: "
                    "paged KV-cache + continuous batching through "
                    "serve.decode, gated device-blind on zero post-warmup "
                    "recompiles across ragged generation lengths, "
                    "MX706/MX709 clean over the decode graphs, and the "
                    "statically priced capacity matching the runtime "
                    "block pool's admission limit")
    ap.add_argument("--int8", action="store_true",
                    help="run the calibrated int8 serving section "
                    "instead: the quantized-zoo twin "
                    "(models.quantized_smoke) of --model, gated "
                    "device-blind on the MX71x staging lint, MX709 "
                    "ladder feasibility at HALF the f32 budget, zero "
                    "post-warmup recompiles over the quantized buckets, "
                    "and bytes/step strictly below the f32 twin")
    ap.add_argument("--cache-dir", default=None,
                    help="artifact-cache root for --replicas (default: "
                    "a fresh temp dir)")
    ap.add_argument("--out", default=None, help="also write JSON here")
    ap.add_argument("--trace-out", default=None,
                    help="write the completed span ring as OTel-style "
                    "span JSONL (one span per line) — the file "
                    "tools/telemetry_check.py --require-rooted-traces "
                    "validates in the trace-smoke CI job")
    ap.add_argument("--slo-gate", action="store_true",
                    help="fail (rc=1) when any SLO's multi-window burn "
                    "alert fires over the run (the chaos drill's "
                    "pass/fail hook; objectives tune via MXTPU_SLO_*)")
    ap.add_argument("--overhead-gate", action="store_true",
                    help="fail (rc=1) when the tracing-overhead A/B "
                    "exceeds its 3%% p50 budget (the telemetry-smoke "
                    "CI hook). Classic path only: replicated/chaos "
                    "modes skip the A/B (their proxy model is "
                    "deliberately un-warmed), so combining them with "
                    "this flag is an error, not a vacuous pass")
    args = ap.parse_args(argv)
    if args.decode:
        n = args.requests if args.requests != 1000 else (
            12 if args.smoke else 64)
        return decode_run(n, args.smoke, out_path=args.out)
    if args.int8:
        n = args.requests if args.requests != 1000 else (
            40 if args.smoke else 400)
        deadline = args.deadline_ms if args.deadline_ms is not None else \
            float(os.environ.get("MXTPU_SERVE_DEADLINE_MS", "5"))
        return int8_run(args.model, n, args.clients, deadline,
                        min(args.iters, 5) if args.smoke else args.iters,
                        out_path=args.out)
    if args.chaos_replicas and args.replicas <= 0:
        args.replicas = 3

    from incubator_mxnet_tpu import profiler, serve
    from incubator_mxnet_tpu.telemetry import goodput as _goodput
    from incubator_mxnet_tpu.telemetry import memory as _memory

    # device-memory ledger: MXTPU_MEMORY_SAMPLE_S > 0 runs the
    # background sampler over the whole bench (the CI memory-smoke
    # config — a steady-state growth trips memory.leak, which
    # telemetry_check --forbid memory.leak turns into a failed job)
    _memory.start_from_env()
    # goodput ledger: MXTPU_GOODPUT=1 anchors the run clock here, so
    # the bench's checkpoint/input notes (weight-sync saves, prefetch
    # waits) attribute against the whole bench wall
    _goodput.begin_from_env()
    if args.smoke:
        args.iters = min(args.iters, 5)
    deadline = args.deadline_ms if args.deadline_ms is not None else \
        float(os.environ.get("MXTPU_SERVE_DEADLINE_MS", "5"))

    net, table, spec, make_request = _build(args.model, args.smoke)
    model = serve.CompiledModel(
        net, table, spec["input_axes"], output_axes=spec["output_axes"],
        pad_values=spec["pad_values"])
    # staging-time compiled-graph lint (the same gate ModelRegistry.load
    # applies): trace-only, so it runs before the first warmup compile;
    # cover every bucket so the record can't claim more than it checked
    from incubator_mxnet_tpu.analysis import hlo as _hlo
    analysis_rep = _hlo.verify(model,
                               max_graphs=max(8, table.num_buckets()))
    if analysis_rep.errors:
        # fail in seconds, not after the full warmup + 1k-request run —
        # same staging semantics as ModelRegistry.load
        print(json.dumps({
            "metric": f"serve_{args.model}_throughput_req_per_sec",
            "value": None, "unit": "req/sec", "vs_baseline": None,
            "error": "analysis_failed",
            "extra": {"model": args.model,
                      "analysis": analysis_rep.summary_dict()}}))
        print("serve_bench: analysis.hlo found "
              f"{len(analysis_rep.errors)} error-severity MX7xx "
              f"finding(s): {[d.code for d in analysis_rep.errors]}",
              file=sys.stderr)
        return 1
    # device-blind perf-proxy record (the serving sibling of bench.py
    # --proxy): price every bucket graph before warmup — trace-only, so
    # a cost explosion is visible even if warmup would then be slow
    cost_rep = _hlo.cost(model, max_graphs=max(8, table.num_buckets()))
    # SLO burn-rate monitoring brackets the run: the pre-run evaluation
    # anchors every window, the post-run gate() computes burn over the
    # run's deltas — so a drill that "recovers" while silently shedding
    # traffic fails its availability objective even when every
    # individual assertion passed
    from incubator_mxnet_tpu.telemetry import slo as _slo
    slo_mon = _slo.SLOMonitor()
    slo_mon.evaluate()
    t0 = time.perf_counter()
    replicated = None
    if args.replicas > 0:
        # HA mode: the replicas warm their own compiled models (prewarmed
        # from the shared artifact cache), so the proxy model stays
        # un-warmed — its cost record is trace-only either way
        import tempfile
        profiler.reset_spans()
        warm, sweep = None, []
        cache_root = args.cache_dir or tempfile.mkdtemp(
            prefix="serve_bench_cache_")
        replicated = replicated_run(
            net, table, spec, make_request, args.requests, args.clients,
            deadline, args.replicas, chaos=args.chaos_replicas,
            cache_root=cache_root)
        dyn = replicated
    else:
        warm = model.warmup()
        profiler.reset_spans()
        sweep = offline_sweep(model, table, make_request, args.iters)
        dyn = dynamic_run(model, spec, make_request, args.requests,
                          args.clients, deadline)
    spans = profiler.span_records()
    step_rep = profiler.step_report(frame="serve.predict")
    proxy = {
        "graphs": len(cost_rep.rows),
        "flops_per_step": cost_rep.model_flops_per_step(),
        "bytes_per_step": cost_rep.bytes_per_step(),
        "peak_live_bytes": cost_rep.peak_live_bytes(),
        "ladder_peak_bytes": cost_rep.ladder_peak_bytes(),
        "fusion_candidates": (cost_rep.head.fusion_candidates
                              if cost_rep.head else 0),
        "transcendentals": (cost_rep.head.transcendentals
                            if cost_rep.head else 0),
        "host_gap_ms": step_rep["host_gap_ms_mean"],
        "instrumented_pct": step_rep["instrumented_pct"],
    }
    from incubator_mxnet_tpu import telemetry
    telemetry.emit("perf.proxy", family=args.model, **proxy)

    slo_ok, slo_rep = slo_mon.gate()
    # the tracing tax A/B needs the warmed classic-path model (in HA
    # mode the local proxy model is deliberately un-warmed — probing it
    # would put post-warmup compiles on the ledger the drill gates on)
    # 200-iteration floor: the probe is a ~0.2ms op, and a p50 over 50
    # samples wobbles past the 3% budget on pure timer noise — at 200
    # the measured tax converges (<0.5% on an idle box)
    overhead = (tracing_overhead(model, make_request, max(args.iters, 200))
                if replicated is None else None)

    best = (max(sweep, key=lambda r: r["rows_per_sec"]) if sweep else None)
    result = {
        "metric": f"serve_{args.model}_throughput_req_per_sec",
        "value": dyn["throughput_req_per_sec"],
        "unit": "req/sec",
        "vs_baseline": None,
        "extra": {
            "model": args.model,
            "backend": jax.default_backend(),
            "warmup": warm,
            "offline_sweep": sweep,
            "offline_best": best,
            "dynamic": dyn,  # in HA mode this IS the replicated record
            "stage_spans": {k: spans[k] for k in sorted(spans)
                            if k.startswith("serve.")},
            "proxy": proxy,
            "step_report": step_rep,
            "analysis": analysis_rep.summary_dict(),
            "tracing_overhead": overhead,
            "slo": {"ok": slo_ok, "slos": slo_rep},
            # the device-memory ledger's closing view: residency, site
            # attribution, leak-watchdog state over the run
            "memory": _memory.snapshot(),
            # the goodput ledger's closing view (enabled-off shape when
            # MXTPU_GOODPUT is unset — one env read)
            "goodput": _goodput.snapshot(),
            "wall_total_s": round(time.perf_counter() - t0, 1),
        },
    }
    _memory.stop()
    doc = json.dumps(result)
    print(doc)
    if args.out:
        with open(args.out, "w") as f:
            f.write(doc + "\n")
    if args.trace_out:
        from incubator_mxnet_tpu.telemetry import export as _export
        with open(args.trace_out, "w") as f:
            for rec in _export.otel_spans():
                f.write(_export.dumps_strict(rec, sort_keys=True) + "\n")
    if dyn["errors"]:
        print(f"serve_bench: {len(dyn['errors'])} client error(s): "
              f"{dyn['errors']}", file=sys.stderr)
        return 1
    # zero-recompile contract: per-model counters on the classic path,
    # the process-wide compile ledger over every replica in HA mode
    if replicated is not None:
        from incubator_mxnet_tpu.telemetry import compile_log
        try:
            compile_log.assert_zero_post_warmup()
        except Exception as e:  # noqa: BLE001 — the gate's evidence
            print(f"serve_bench: ZERO-RECOMPILE CONTRACT VIOLATED "
                  f"(compile ledger): {e}", file=sys.stderr)
            return 1
        if replicated["silent_drops"]:
            print(f"serve_bench: {replicated['silent_drops']} accepted "
                  "request(s) vanished without a result, a shed, or an "
                  "error — the zero-silent-drop contract is violated",
                  file=sys.stderr)
            return 1
        if args.chaos_replicas:
            states = replicated["replica_states"]
            if not all(s == "healthy" for s in states.values()):
                print(f"serve_bench: replica(s) did not rejoin healthy "
                      f"after the chaos drill: {states}", file=sys.stderr)
                return 1
            # prewarm-from-cache contract: the ledger cannot see a
            # restart retrace (a fresh CompiledModel's compiles are
            # warmup-phase by construction), so gate on the cache
            # outcomes themselves — exactly one cold miss (first boot),
            # exactly the injected corruption, and every other load a
            # verified HIT (no source-model retrace anywhere else)
            pc = replicated["prewarm_cache"]
            if pc["misses"] != 1 or pc["corrupt"] != 1 \
                    or pc["hits"] < args.replicas - 1:
                print("serve_bench: PREWARM-FROM-CACHE CONTRACT "
                      f"VIOLATED: {pc} (want exactly 1 cold miss, the 1 "
                      "injected corruption, and verified hits "
                      "everywhere else)", file=sys.stderr)
                return 1
            from incubator_mxnet_tpu import lockcheck
            try:
                lockcheck.assert_no_inversions()
            except lockcheck.LockOrderError as e:
                print(f"serve_bench: {e}", file=sys.stderr)
                return 1
    else:
        recompiles = dyn["compile_cache"]["post_warmup_compiles"]
        if recompiles:
            print(f"serve_bench: ZERO-RECOMPILE CONTRACT VIOLATED: "
                  f"{recompiles} post-warmup compile(s)", file=sys.stderr)
            return 1
    if replicated is not None:
        # the trace-smoke contract: with head sampling at 1.0 every
        # completed request must stitch into exactly one rooted tree
        # (hedges/failovers as siblings under one parent) and the whole
        # ring must hold zero orphan spans
        from incubator_mxnet_tpu.telemetry import trace as _trace
        tr = replicated["tracing"]
        if _trace.sample_rate() >= 1.0:
            bad = (tr["forests"] or tr["missing"] or tr["orphan_spans"]
                   or tr["rooted_trees"] != tr["requests_traced"]
                   or not tr["requests_traced"])
            if bad:
                print("serve_bench: ROOTED-TRACE CONTRACT VIOLATED "
                      f"(sampling=1.0): {tr} — every sampled request "
                      "must yield a single rooted span tree, zero "
                      "orphans", file=sys.stderr)
                return 1
    if args.overhead_gate and overhead is None:
        # vacuous pass is worse than a loud failure: the operator asked
        # for the budget to be enforced and nothing was measured
        print("serve_bench: --overhead-gate requires the classic "
              "(non-replicated) path — the A/B probes the warmed local "
              "model, which HA mode deliberately leaves un-warmed. "
              "Re-run without --replicas/--chaos-replicas.",
              file=sys.stderr)
        return 1
    if args.overhead_gate and not overhead["pass"]:
        print("serve_bench: TRACING OVERHEAD BUDGET EXCEEDED: "
              f"{overhead} — p50 regression with sampling on must stay "
              f"under {overhead['budget_pct']}%", file=sys.stderr)
        return 1
    if args.slo_gate and not slo_ok:
        burning = [n for n, r in slo_rep.items() if r["breach"]]
        print(f"serve_bench: SLO BURN ALERT over the run: {burning} "
              f"({json.dumps({n: slo_rep[n]['burn'] for n in burning})})",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""serve_bench — offline throughput/latency sweep + dynamic-batching demo.

The serving counterpart of ``bench.py`` (which measures training steps):
one command produces a BENCH-style JSON record covering

1. **offline sweep**: for each batch-bucket size, steady-state
   ``CompiledModel.predict`` latency and throughput (rows/sec) — the
   padded-batch replay ceiling;
2. **dynamic section** (the ISSUE acceptance demo): N mixed-shape single
   requests pushed through a :class:`DynamicBatcher` from client threads —
   p50/p95/p99 end-to-end latency, throughput, batch occupancy, queue
   high-water, and the compile-cache counters with **zero post-warmup
   recompiles asserted** (rc != 0 on violation);
3. per-stage wall time from the profiler span recorder
   (pad / compute / unpad / batch), a ``serve.predict`` host-gap
   attribution (``profiler.step_report``), and a device-blind perf-proxy
   record (``analysis.hlo.cost`` FLOPs/bytes/fusion per bucket graph —
   the serving sibling of ``bench.py --proxy``), also emitted as one
   ``perf.proxy`` telemetry event.

Usage::

    python -m benchmark.serve_bench --smoke          # <60 s CPU CI config
    python -m benchmark.serve_bench --model bert --requests 5000
    python -m benchmark.serve_bench --out serve_bench.json

Env: ``MXTPU_SERVE_BENCH_MODEL`` (mlp|lenet|bert), ``MXTPU_SERVE_BENCH_N``
(request count) mirror the flags for harness use.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=1")
import jax  # noqa: E402

import numpy as onp  # noqa: E402


def _build(model_name: str, smoke: bool):
    """Returns (net, table, spec, make_request(rng) -> per-example args)."""
    from incubator_mxnet_tpu import models, nd, serve

    if model_name == "bert":
        vocab, max_len = 1000, 64 if smoke else 128
        net = models.get_bert("bert_2_128_2", vocab_size=vocab,
                              max_length=max_len, dropout=0.1,
                              use_decoder=False, use_classifier=False)
        net.initialize()
        net.hybridize()
        rng = onp.random.RandomState(0)
        L = 16
        ids = nd.array(rng.randint(1, vocab, (2, L)).astype("int32"))
        tt = nd.array(onp.zeros((2, L), "int32"))
        vl = nd.array(onp.full((2,), L, "float32"))
        net(ids, tt, vl)
        table = serve.BucketTable({"batch": (1, 8 if smoke else 32),
                                   "seq": (8, 32 if smoke else max_len)})
        spec = models.serve_spec("bert_encoder")

        def make_request(rng):
            L = int(rng.randint(4, (32 if smoke else max_len) - 1))
            return (rng.randint(1, vocab, (L,)).astype("int32"),
                    onp.zeros((L,), "int32"), onp.float32(L))

        return net, table, spec, make_request

    if model_name == "lenet":
        net = models.LeNet()
        net.initialize()
        net.hybridize()
        from incubator_mxnet_tpu import nd
        x = nd.array(onp.zeros((2, 1, 28, 28), "float32"))
        net(x)
        table = serve.BucketTable({"batch": (1, 16 if smoke else 64)})
        spec = models.serve_spec("lenet")

        def make_request(rng):
            return (rng.randn(1, 28, 28).astype("float32"),)

        return net, table, spec, make_request

    # mlp: the fastest smoke model
    from incubator_mxnet_tpu import gluon, nd
    net = gluon.nn.HybridSequential(prefix="servebench_")
    with net.name_scope():
        net.add(gluon.nn.Dense(64, activation="relu", in_units=32))
        net.add(gluon.nn.Dense(8, in_units=64))
    net.initialize()
    net.hybridize()
    net(nd.array(onp.zeros((2, 32), "float32")))
    table = serve.BucketTable({"batch": (1, 16 if smoke else 64)})
    spec = {"input_axes": [{0: "batch"}], "output_axes": [{0: "batch"}],
            "pad_values": [0]}

    def make_request(rng):
        return (rng.randn(32).astype("float32"),)

    return net, table, spec, make_request


def offline_sweep(model, table, make_request, iters: int):
    """Steady-state padded-batch latency per batch bucket."""
    from incubator_mxnet_tpu.serve.batcher import stack_examples

    rows = []
    rng = onp.random.RandomState(1)
    axis = model._primary_axis
    for bucket in table.sizes(axis):
        reqs = [make_request(rng) for _ in range(bucket)]
        # mixed per-request lengths (bert): pad to the batch max exactly
        # like a batcher flush would
        stacked = stack_examples(model, reqs)
        model.predict(*stacked)  # steady-state: bucket already warmed
        t0 = time.perf_counter()
        for _ in range(iters):
            out = model.predict(*stacked)
        out = out[0] if isinstance(out, tuple) else out
        out.asnumpy()  # sync
        dt = (time.perf_counter() - t0) / iters
        rows.append({"batch": bucket, "latency_ms": round(dt * 1e3, 3),
                     "rows_per_sec": round(bucket / dt, 1)})
    return rows


def dynamic_run(model, spec, make_request, n_requests: int,
                clients: int, deadline_ms: float):
    from incubator_mxnet_tpu import serve

    batcher = serve.DynamicBatcher(model, max_delay_ms=deadline_ms).start()
    errors = []
    lock = threading.Lock()

    def client(cid: int):
        rng = onp.random.RandomState(100 + cid)
        n = n_requests // clients
        for _ in range(n):
            try:
                fut = batcher.submit(*make_request(rng))
                fut.result(timeout=120)
            except Exception as e:  # noqa: BLE001 — collected for the report
                with lock:
                    errors.append(f"{type(e).__name__}: {e}")
                return

    t0 = time.perf_counter()
    threads = [threading.Thread(target=client, args=(c,),
                                name=f"bench-client-{c}", daemon=False)
               for c in range(clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    snap = batcher.metrics.snapshot(model)
    batcher.stop()
    served = snap["requests"]
    return {
        "requests": served,
        "wall_s": round(wall, 3),
        "throughput_req_per_sec": round(served / wall, 1) if wall else 0.0,
        "clients": clients,
        "deadline_ms": deadline_ms,
        "errors": errors[:5],
        **{k: snap[k] for k in ("latency", "batch_latency",
                                "batch_occupancy", "queue_max_depth",
                                "batches", "rejected")},
        "compile_cache": snap["compile_cache"],
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--model", default=os.environ.get(
        "MXTPU_SERVE_BENCH_MODEL", "mlp"), choices=["mlp", "lenet", "bert"])
    ap.add_argument("--requests", type=int, default=int(os.environ.get(
        "MXTPU_SERVE_BENCH_N", "1000")))
    ap.add_argument("--clients", type=int, default=4)
    ap.add_argument("--iters", type=int, default=20,
                    help="offline timed iterations per bucket")
    ap.add_argument("--deadline-ms", type=float, default=None)
    ap.add_argument("--smoke", action="store_true",
                    help="<60s CPU config: small buckets, fewer iters")
    ap.add_argument("--out", default=None, help="also write JSON here")
    args = ap.parse_args(argv)

    from incubator_mxnet_tpu import profiler, serve

    if args.smoke:
        args.iters = min(args.iters, 5)
    deadline = args.deadline_ms if args.deadline_ms is not None else \
        float(os.environ.get("MXTPU_SERVE_DEADLINE_MS", "5"))

    net, table, spec, make_request = _build(args.model, args.smoke)
    model = serve.CompiledModel(
        net, table, spec["input_axes"], output_axes=spec["output_axes"],
        pad_values=spec["pad_values"])
    # staging-time compiled-graph lint (the same gate ModelRegistry.load
    # applies): trace-only, so it runs before the first warmup compile;
    # cover every bucket so the record can't claim more than it checked
    from incubator_mxnet_tpu.analysis import hlo as _hlo
    analysis_rep = _hlo.verify(model,
                               max_graphs=max(8, table.num_buckets()))
    if analysis_rep.errors:
        # fail in seconds, not after the full warmup + 1k-request run —
        # same staging semantics as ModelRegistry.load
        print(json.dumps({
            "metric": f"serve_{args.model}_throughput_req_per_sec",
            "value": None, "unit": "req/sec", "vs_baseline": None,
            "error": "analysis_failed",
            "extra": {"model": args.model,
                      "analysis": analysis_rep.summary_dict()}}))
        print("serve_bench: analysis.hlo found "
              f"{len(analysis_rep.errors)} error-severity MX7xx "
              f"finding(s): {[d.code for d in analysis_rep.errors]}",
              file=sys.stderr)
        return 1
    # device-blind perf-proxy record (the serving sibling of bench.py
    # --proxy): price every bucket graph before warmup — trace-only, so
    # a cost explosion is visible even if warmup would then be slow
    cost_rep = _hlo.cost(model, max_graphs=max(8, table.num_buckets()))
    t0 = time.perf_counter()
    warm = model.warmup()
    profiler.reset_spans()

    sweep = offline_sweep(model, table, make_request, args.iters)
    dyn = dynamic_run(model, spec, make_request, args.requests,
                      args.clients, deadline)
    spans = profiler.span_records()
    step_rep = profiler.step_report(frame="serve.predict")
    proxy = {
        "graphs": len(cost_rep.rows),
        "flops_per_step": cost_rep.model_flops_per_step(),
        "bytes_per_step": cost_rep.bytes_per_step(),
        "fusion_candidates": (cost_rep.head.fusion_candidates
                              if cost_rep.head else 0),
        "transcendentals": (cost_rep.head.transcendentals
                            if cost_rep.head else 0),
        "host_gap_ms": step_rep["host_gap_ms_mean"],
        "instrumented_pct": step_rep["instrumented_pct"],
    }
    from incubator_mxnet_tpu import telemetry
    telemetry.emit("perf.proxy", family=args.model, **proxy)

    best = max(sweep, key=lambda r: r["rows_per_sec"])
    recompiles = dyn["compile_cache"]["post_warmup_compiles"]
    result = {
        "metric": f"serve_{args.model}_throughput_req_per_sec",
        "value": dyn["throughput_req_per_sec"],
        "unit": "req/sec",
        "vs_baseline": None,
        "extra": {
            "model": args.model,
            "backend": jax.default_backend(),
            "warmup": warm,
            "offline_sweep": sweep,
            "offline_best": best,
            "dynamic": dyn,
            "stage_spans": {k: spans[k] for k in sorted(spans)
                            if k.startswith("serve.")},
            "proxy": proxy,
            "step_report": step_rep,
            "analysis": analysis_rep.summary_dict(),
            "wall_total_s": round(time.perf_counter() - t0, 1),
        },
    }
    doc = json.dumps(result)
    print(doc)
    if args.out:
        with open(args.out, "w") as f:
            f.write(doc + "\n")
    if dyn["errors"]:
        print(f"serve_bench: {len(dyn['errors'])} client error(s): "
              f"{dyn['errors']}", file=sys.stderr)
        return 1
    if recompiles:
        print(f"serve_bench: ZERO-RECOMPILE CONTRACT VIOLATED: "
              f"{recompiles} post-warmup compile(s)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""BERT pretraining with the sharded SPMD trainer.

Reference counterpart: GluonNLP ``scripts/bert/run_pretraining.py`` (the
BASELINE.json north-star recipe). One compiled step — embeddings, flash
attention encoder, MLM+NSP heads, AdamW with fp32 master weights — over a
``dp×tp×sp`` mesh; on one chip the mesh is 1×1×1 and the same program runs
unchanged. Uses synthetic masked-LM batches (no network access).

    python examples/bert_pretraining.py --model bert_2_128_2 --steps 20
    python examples/bert_pretraining.py --dp 2 --tp 2   # on an 8-chip host
"""
import argparse
import os
import sys

import numpy as onp

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
import incubator_mxnet_tpu as mx  # noqa: E402,F401
from incubator_mxnet_tpu import models, parallel  # noqa: E402


def synthetic_batch(rng, B, L, P, vocab):
    ids = rng.randint(0, vocab, (B, L)).astype("int32")
    token_types = rng.randint(0, 2, (B, L)).astype("int32")
    valid_len = onp.full((B,), L, "float32")
    positions = rng.randint(0, L, (B, P)).astype("int32")
    mlm_labels = rng.randint(0, vocab, (B, P)).astype("float32")
    mlm_weights = onp.ones((B, P), "float32")
    nsp_labels = rng.randint(0, 2, (B,)).astype("float32")
    return (ids, token_types, valid_len, positions, mlm_labels, mlm_weights,
            nsp_labels)


def main(argv=None) -> float:
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="bert_2_128_2",
                    choices=sorted(models.bert.BERT_CONFIGS))
    ap.add_argument("--batch-size", type=int, default=8)
    ap.add_argument("--seq-len", type=int, default=128)
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--lr", type=float, default=1e-4)
    ap.add_argument("--dtype", default="bfloat16")
    ap.add_argument("--dp", type=int, default=1)
    ap.add_argument("--tp", type=int, default=1)
    ap.add_argument("--sp", type=int, default=1)
    ap.add_argument("--remat", action="store_true",
                    help="jax.checkpoint per encoder layer")
    ap.add_argument("--zero1", action="store_true",
                    help="shard optimizer states over dp (ZeRO-1)")
    ap.add_argument("--ckpt-dir", default=None,
                    help="mx.fault checkpoint directory: resumes from the "
                         "newest verified step on start, saves every "
                         "--ckpt-every steps (atomic; kill-safe)")
    ap.add_argument("--ckpt-every", type=int, default=100)
    ap.add_argument("--seed", type=int, default=None,
                    help="RNG seed; default: MXNET_TEST_SEED or 42")
    args = ap.parse_args(argv)

    # deterministic init (reference train.py seeds) — MXNET_TEST_SEED wins
    # so the committed seed-sweep actually varies the init across runs
    mx.random.seed(args.seed if args.seed is not None
                   else int(os.environ.get("MXNET_TEST_SEED", "42")))

    vocab = 1000 if args.model == "bert_2_128_2" else 30522
    P = max(1, round(0.15 * args.seq_len))
    net = models.get_bert(args.model, vocab_size=vocab,
                          max_length=args.seq_len, dropout=0.1,
                          dtype=args.dtype, remat=args.remat)
    net.initialize()
    # mesh over exactly the devices the requested axes need (1×1×1 = one
    # chip), so the same script runs on a single chip or a pod slice
    import jax
    n_dev = args.dp * args.tp * args.sp
    mesh = parallel.make_mesh(devices=jax.devices()[:n_dev],
                              dp=args.dp, tp=args.tp, sp=args.sp)
    trainer = parallel.ShardedTrainer(
        net, models.bert_pretrain_loss, "adamw",
        {"learning_rate": args.lr, "multi_precision": True}, mesh=mesh,
        rules=models.bert_sharding_rules(), n_labels=3,
        seq_axis=1 if args.sp > 1 else None, zero1=args.zero1)

    rng = onp.random.RandomState(0)
    batch = synthetic_batch(rng, args.batch_size, args.seq_len, P, vocab)
    loss = trainer.step(*batch)  # compile
    start = 0
    if args.ckpt_dir:
        try:
            resumed = trainer.restore_checkpoint(args.ckpt_dir)
            # checkpoint steps count the compile step too; finish only the
            # REMAINING work instead of re-running the full budget
            start = min(max(resumed - 1, 0), args.steps)
            print(f"resumed from checkpoint step {resumed}; "
                  f"{args.steps - start} step(s) remaining")
        except mx.fault.CheckpointError:
            pass  # cold start: nothing saved yet
    placed = trainer.place(*batch)
    last = None
    for step in range(start, args.steps):
        loss = trainer.step(*placed)
        if args.ckpt_dir and (step % args.ckpt_every == 0
                              or step == args.steps - 1):
            trainer.save_checkpoint(args.ckpt_dir)
        if step % 5 == 0 or step == args.steps - 1:
            last = float(loss.asnumpy())
            print(f"step {step:4d}  loss {last:.4f}")
    return last


if __name__ == "__main__":
    main()

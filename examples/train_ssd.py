#!/usr/bin/env python
"""SSD end-to-end detection training on a synthetic shapes dataset.

Reference counterpart: GluonCV ``scripts/detection/ssd/train_ssd.py``
(SURVEY §2.9, BASELINE.json configs[4]). The pipeline is the full SSD
recipe — multi-scale anchors (``multibox_prior``), target matching with
hard-negative mining (``multibox_target``), CE + SmoothL1 loss, NMS decode
(``multibox_detection``) — on a dataset this image can generate offline:
one axis-aligned bright rectangle per image, class = which RGB channel is
lit. Reports a detection-accuracy mAP proxy: the fraction of held-out
images whose top detection has the right class and IoU > 0.5.

    python examples/train_ssd.py [--steps N] [--image-size 48]
"""
import argparse
import os
import sys

import numpy as onp

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
import incubator_mxnet_tpu as mx  # noqa: E402
from incubator_mxnet_tpu import gluon, models, nd  # noqa: E402


def make_dataset(rng, n, size):
    """(images (n, 3, S, S), labels (n, 1, 5)): one colored rectangle on a
    dim noisy background; class = color channel."""
    imgs = 0.1 * rng.rand(n, 3, size, size).astype("float32")
    labels = onp.zeros((n, 1, 5), "float32")
    for i in range(n):
        cls = rng.randint(0, 3)
        w = rng.randint(size // 4, size // 2 + 1)
        h = rng.randint(size // 4, size // 2 + 1)
        x0 = rng.randint(0, size - w)
        y0 = rng.randint(0, size - h)
        imgs[i, cls, y0:y0 + h, x0:x0 + w] = 1.0
        labels[i, 0] = [cls, x0 / size, y0 / size,
                        (x0 + w) / size, (y0 + h) / size]
    return imgs, labels


def _iou(a, b):
    ix = max(0.0, min(a[2], b[2]) - max(a[0], b[0]))
    iy = max(0.0, min(a[3], b[3]) - max(a[1], b[1]))
    inter = ix * iy
    ua = (a[2] - a[0]) * (a[3] - a[1]) + (b[2] - b[0]) * (b[3] - b[1]) - inter
    return inter / ua if ua > 0 else 0.0


def evaluate(net, imgs, labels, batch_size=16):
    """mAP proxy: top-detection hit rate (class right, IoU > 0.5)."""
    hits, total = 0, 0
    for s in range(0, len(imgs), batch_size):
        x = nd.array(imgs[s:s + batch_size])
        det = net.detect(x, threshold=0.01).asnumpy()  # (B, N, 6)
        for b in range(det.shape[0]):
            rows = det[b]
            rows = rows[rows[:, 0] >= 0]
            total += 1
            if rows.size == 0:
                continue
            best = rows[rows[:, 1].argmax()]
            truth = labels[s + b, 0]
            if int(best[0]) == int(truth[0]) and \
                    _iou(best[2:6], truth[1:5]) > 0.5:
                hits += 1
    return hits / max(total, 1)


def main(argv=None) -> float:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=300)
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--image-size", type=int, default=48)
    ap.add_argument("--train-size", type=int, default=256)
    ap.add_argument("--val-size", type=int, default=64)
    ap.add_argument("--lr", type=float, default=0.005)
    ap.add_argument("--momentum", type=float, default=0.9)
    ap.add_argument("--ckpt-dir", default=None,
                    help="mx.fault checkpoint directory (atomic periodic "
                         "checkpoints; kill-safe)")
    ap.add_argument("--seed", type=int, default=None,
                    help="RNG seed; default: MXNET_TEST_SEED or 42")
    args = ap.parse_args(argv)

    # deterministic init (reference train_ssd.py seeds) — MXNET_TEST_SEED
    # wins so the committed seed-sweep actually varies the init across runs
    mx.random.seed(args.seed if args.seed is not None
                   else int(os.environ.get("MXNET_TEST_SEED", "42")))
    rng = onp.random.RandomState(0)   # the dataset itself stays fixed
    tr_x, tr_y = make_dataset(rng, args.train_size, args.image_size)
    va_x, va_y = make_dataset(rng, args.val_size, args.image_size)

    net = models.SSD(num_classes=3)
    net.initialize(mx.init.Xavier())
    net.hybridize()
    loss_fn = models.SSDTargetLoss()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": args.lr,
                             "momentum": args.momentum})

    B = args.batch_size
    for step in range(args.steps):
        idx = rng.randint(0, args.train_size, B)
        x, y = nd.array(tr_x[idx]), nd.array(tr_y[idx])
        with mx.autograd.record():
            cp, bp, an = net(x)
            loss = loss_fn(cp, bp, an, y)
        loss.backward()
        trainer.step(1)   # SSDTargetLoss already normalizes by num_pos
        if step % 50 == 0:
            if args.ckpt_dir:
                trainer.save_checkpoint(args.ckpt_dir)
            print(f"step {step:4d} loss {float(loss.asnumpy()):.4f}")

    acc = evaluate(net, va_x, va_y)
    print(f"detection accuracy (mAP proxy): {acc:.4f}")
    return acc


if __name__ == "__main__":
    main()

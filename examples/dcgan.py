#!/usr/bin/env python
"""DCGAN: adversarial generator/discriminator training with Gluon.

Reference counterpart: ``example/gluon/dcgan.py`` — transposed-conv
generator vs strided-conv discriminator, BatchNorm + ReLU / LeakyReLU,
sigmoid-BCE on real/fake labels, separate Adam trainers. Scaled to run
anywhere: "images" are 16x16 synthetic discs whose radius/intensity vary
(no CelebA/LSUN download in this image); success is the generator matching
the real data's first moments while the discriminator stays near chance.

    python examples/dcgan.py --steps 200
"""
import argparse
import os
import sys

import numpy as onp

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
import incubator_mxnet_tpu as mx  # noqa: E402
from incubator_mxnet_tpu import gluon, nd  # noqa: E402
from incubator_mxnet_tpu.gluon import nn  # noqa: E402


def build_generator(latent):
    # reference dcgan.py netG: Dense-projected seed, then
    # Conv2DTranspose/BN/ReLU doublings up to the image size, tanh output
    net = nn.HybridSequential(prefix="gen_")
    with net.name_scope():
        net.add(nn.Dense(4 * 4 * 32, in_units=latent))
        net.add(nn.HybridLambda(lambda F, x: x.reshape((-1, 32, 4, 4))))
        net.add(nn.BatchNorm(in_channels=32))
        net.add(nn.Activation("relu"))
        net.add(nn.Conv2DTranspose(16, 4, strides=(2, 2), padding=(1, 1),
                                   in_channels=32, use_bias=False))  # 8x8
        net.add(nn.BatchNorm(in_channels=16))
        net.add(nn.Activation("relu"))
        net.add(nn.Conv2DTranspose(1, 4, strides=(2, 2), padding=(1, 1),
                                   in_channels=16))                  # 16x16
        net.add(nn.Activation("tanh"))
    return net


def build_discriminator():
    # reference dcgan.py netD: strided convs + LeakyReLU(0.2), no sigmoid
    # (the loss consumes raw logits)
    net = nn.HybridSequential(prefix="disc_")
    with net.name_scope():
        net.add(nn.Conv2D(16, 4, strides=(2, 2), padding=(1, 1),
                          in_channels=1))                            # 8x8
        net.add(nn.LeakyReLU(0.2))
        net.add(nn.Conv2D(32, 4, strides=(2, 2), padding=(1, 1),
                          in_channels=16, use_bias=False))           # 4x4
        net.add(nn.BatchNorm(in_channels=32))
        net.add(nn.LeakyReLU(0.2))
        net.add(nn.Dense(1, in_units=32 * 4 * 4))
    return net


def real_batch(rng, n, size=16):
    """Discs of varying radius/intensity on a dark field, in [-1, 1]."""
    yy, xx = onp.mgrid[:size, :size]
    d2 = (yy - size / 2 + 0.5) ** 2 + (xx - size / 2 + 0.5) ** 2
    radius = rng.uniform(3.0, 6.0, (n, 1, 1))
    bright = rng.uniform(0.6, 1.0, (n, 1, 1))
    img = onp.where(d2[None] <= radius ** 2, bright, -0.9)
    return img[:, None].astype("float32")  # NCHW


def main(argv=None) -> dict:
    ap = argparse.ArgumentParser()
    ap.add_argument("--latent", type=int, default=32)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--steps", type=int, default=200)
    ap.add_argument("--lr", type=float, default=2e-4)
    ap.add_argument("--beta1", type=float, default=0.5)
    ap.add_argument("--ckpt-dir", default=None,
                    help="mx.fault checkpoint root (atomic periodic "
                         "checkpoints for both trainers; kill-safe)")
    args = ap.parse_args(argv)

    # MXNET_TEST_SEED wins so the committed seed-sweep varies the init
    mx.random.seed(int(os.environ.get("MXNET_TEST_SEED", "11")))
    rng = onp.random.RandomState(11)
    netG = build_generator(args.latent)
    netD = build_discriminator()
    netG.initialize(mx.init.Normal(0.02))
    netD.initialize(mx.init.Normal(0.02))
    trainerG = gluon.Trainer(netG.collect_params(), "adam",
                             {"learning_rate": args.lr, "beta1": args.beta1})
    trainerD = gluon.Trainer(netD.collect_params(), "adam",
                             {"learning_rate": args.lr, "beta1": args.beta1})
    loss_fn = gluon.loss.SigmoidBinaryCrossEntropyLoss()

    B = args.batch_size
    ones = nd.array(onp.ones((B, 1), "float32"))
    zeros = nd.array(onp.zeros((B, 1), "float32"))
    d_acc_hist = []
    for step in range(args.steps):
        real = nd.array(real_batch(rng, B))
        z = nd.array(rng.randn(B, args.latent).astype("float32"))
        # --- D step: maximize log D(x) + log(1 - D(G(z))); the fake batch is
        # generated under record (BatchNorm batch stats, reference dcgan.py
        # semantics) but detached so only D's gradients flow
        with mx.autograd.record():
            fake = netG(z).detach()
            out_real = netD(real)
            out_fake = netD(fake)
            lossD = (loss_fn(out_real, ones) + loss_fn(out_fake, zeros)).mean()
        lossD.backward()
        trainerD.step(1)
        # --- G step: maximize log D(G(z))
        z = nd.array(rng.randn(B, args.latent).astype("float32"))
        with mx.autograd.record():
            lossG = loss_fn(netD(netG(z)), ones).mean()
        lossG.backward()
        trainerG.step(1)
        pr = 1.0 / (1.0 + onp.exp(-out_real.asnumpy()))
        pf = 1.0 / (1.0 + onp.exp(-out_fake.asnumpy()))
        d_acc_hist.append(((pr > 0.5).mean() + (pf < 0.5).mean()) / 2)
        if args.ckpt_dir and (step % 50 == 0 or step == args.steps - 1):
            trainerD.save_checkpoint(os.path.join(args.ckpt_dir, "D"))
            trainerG.save_checkpoint(os.path.join(args.ckpt_dir, "G"))
        if step % 50 == 0 or step == args.steps - 1:
            print(f"step {step:4d}  lossD {float(lossD.asnumpy()):.3f}  "
                  f"lossG {float(lossG.asnumpy()):.3f}  "
                  f"D-acc {d_acc_hist[-1]:.2f}")

    # evaluate: generator moments vs the real distribution
    z = nd.array(rng.randn(256, args.latent).astype("float32"))
    with mx.autograd.predict_mode():
        fakes = netG(z).asnumpy()
    reals = real_batch(rng, 256)
    stats = {
        "fake_mean": float(fakes.mean()), "real_mean": float(reals.mean()),
        "fake_std": float(fakes.std()), "real_std": float(reals.std()),
        "d_acc_tail": float(onp.mean(d_acc_hist[-20:])),
    }
    print({k: round(v, 3) for k, v in stats.items()})
    return stats


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""LSTM word-level language model with truncated BPTT and tied embeddings.

Reference counterpart: ``example/rnn/word_lm/train.py`` (the PTB recipe):
Embedding -> multi-layer LSTM -> decoder tied to the embedding weight,
trained by truncated backprop-through-time with hidden-state carry between
chunks, global-norm gradient clipping, and SGD with lr annealing on plateau.
Runs anywhere: the corpus is a synthetic 90%-deterministic Markov chain
(no PTB download in this image), so the learnable optimum has perplexity
~2.1 at vocab 50 while an untrained model sits at ~50.

    python examples/word_language_model.py --steps 60
"""
import argparse
import math
import os
import sys

import numpy as onp

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
import incubator_mxnet_tpu as mx  # noqa: E402
from incubator_mxnet_tpu import gluon, nd  # noqa: E402
from incubator_mxnet_tpu.gluon import nn  # noqa: E402


class RNNModel(gluon.Block):
    """Embedding -> LSTM -> (tied) decoder, reference word_lm model.py."""

    def __init__(self, vocab_size, embed_size, hidden_size, num_layers,
                 dropout=0.2, tied=True, **kwargs):
        super().__init__(**kwargs)
        self._tied = tied
        with self.name_scope():
            self.drop = nn.Dropout(dropout)
            self.encoder = nn.Embedding(vocab_size, embed_size)
            self.rnn = gluon.rnn.LSTM(hidden_size, num_layers, layout="TNC",
                                      dropout=dropout, input_size=embed_size)
            if tied:
                if hidden_size != embed_size:
                    raise ValueError("tied weights need hidden == embed size")
                # reference model.py: nn.Dense(..., params=encoder.params)
                # shares the embedding weight with the output projection
                self.decoder = nn.Dense(vocab_size, flatten=False,
                                        in_units=hidden_size,
                                        params=self.encoder.params)
            else:
                self.decoder = nn.Dense(vocab_size, flatten=False,
                                        in_units=hidden_size)

    def forward(self, inputs, states):
        # inputs: (T, N) int tokens
        emb = self.drop(self.encoder(inputs))
        out, states = self.rnn(emb, states)
        logits = self.decoder(self.drop(out))  # (T, N, V)
        return logits, states

    def begin_state(self, batch_size, **kwargs):
        return self.rnn.begin_state(batch_size, **kwargs)


def make_corpus(length, vocab, rng):
    """90%-deterministic Markov chain: next = (3*cur + 7) % vocab, else
    uniform — entropy floor ~0.73 nats (ppl ~2.1)."""
    toks = onp.empty(length, "int32")
    toks[0] = rng.randint(vocab)
    jumps = rng.rand(length) < 0.1
    noise = rng.randint(0, vocab, length)
    for i in range(1, length):
        toks[i] = noise[i] if jumps[i] else (3 * toks[i - 1] + 7) % vocab
    return toks


def batchify(data, batch_size):
    """(T, N) layout, reference train.py batchify."""
    nbatch = len(data) // batch_size
    return data[: nbatch * batch_size].reshape(batch_size, nbatch).T


def main(argv=None) -> float:
    ap = argparse.ArgumentParser()
    ap.add_argument("--vocab", type=int, default=50)
    ap.add_argument("--emsize", type=int, default=64)
    ap.add_argument("--nhid", type=int, default=64)
    ap.add_argument("--nlayers", type=int, default=2)
    ap.add_argument("--bptt", type=int, default=16)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--steps", type=int, default=60,
                    help="BPTT chunks per epoch")
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--lr", type=float, default=5.0)
    ap.add_argument("--clip", type=float, default=0.25)
    ap.add_argument("--dropout", type=float, default=0.1)
    ap.add_argument("--no-tied", action="store_true")
    ap.add_argument("--ckpt-dir", default=None,
                    help="mx.fault checkpoint directory (atomic per-epoch "
                         "checkpoints; kill-safe)")
    ap.add_argument("--seed", type=int, default=None,
                    help="RNG seed; default: MXNET_TEST_SEED or 42")
    args = ap.parse_args(argv)

    # deterministic init (reference train.py seeds) — MXNET_TEST_SEED wins
    # so the committed seed-sweep actually varies the init across runs
    mx.random.seed(args.seed if args.seed is not None
                   else int(os.environ.get("MXNET_TEST_SEED", "42")))
    rng = onp.random.RandomState(7)
    corpus = batchify(
        make_corpus((args.steps * args.bptt + 1) * args.batch_size + 1,
                    args.vocab, rng), args.batch_size)

    model = RNNModel(args.vocab, args.emsize, args.nhid, args.nlayers,
                     dropout=args.dropout, tied=not args.no_tied)
    model.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(model.collect_params(), "sgd",
                            {"learning_rate": args.lr})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    prev_ppl = float("inf")
    ppl = float("nan")
    for epoch in range(args.epochs):
        states = model.begin_state(args.batch_size)
        total_nll, total_tok = 0.0, 0
        for step in range(args.steps):
            lo = step * args.bptt
            data = nd.array(corpus[lo: lo + args.bptt])
            target = nd.array(
                corpus[lo + 1: lo + 1 + args.bptt].reshape(-1).astype(
                    "float32"))
            states = [s.detach() for s in states]  # truncate the BPTT graph
            with mx.autograd.record():
                logits, states = model(data, states)
                loss = loss_fn(logits.reshape((-1, args.vocab)), target)
                loss = loss.mean()
            loss.backward()
            grads = [p.grad() for p in model.collect_params().values()
                     if p.grad_req != "null"]
            gluon.utils.clip_global_norm(grads, args.clip)
            trainer.step(1)
            total_nll += float(loss.asnumpy()) * data.shape[0] * data.shape[1]
            total_tok += data.shape[0] * data.shape[1]
        ppl = math.exp(total_nll / total_tok)
        if args.ckpt_dir:
            trainer.save_checkpoint(args.ckpt_dir)
        if ppl > prev_ppl:  # reference train.py: anneal lr on plateau
            trainer.set_learning_rate(trainer.learning_rate / 4.0)
        prev_ppl = ppl
        print(f"epoch {epoch}  train ppl {ppl:.2f}  "
              f"lr {trainer.learning_rate:g}")
    return ppl


if __name__ == "__main__":
    main()

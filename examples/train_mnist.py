#!/usr/bin/env python
"""MNIST training via the legacy Module API.

Reference counterpart: ``example/image-classification/train_mnist.py``
(SURVEY §2.9 — the in-tree smoke workload). Uses the symbolic frontend +
``Module.fit`` exactly like the reference script; synthesizes MNIST-shaped
data when the idx files are absent (this image has no network access to
download the real set).

    python examples/train_mnist.py [--network mlp|lenet] [--num-epochs N]
"""
import argparse
import os
import sys

import numpy as onp

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
import incubator_mxnet_tpu as mx  # noqa: E402
from incubator_mxnet_tpu import io as mio  # noqa: E402


def mlp():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=128, name="fc1")
    net = mx.sym.Activation(net, act_type="relu", name="relu1")
    net = mx.sym.FullyConnected(net, num_hidden=64, name="fc2")
    net = mx.sym.Activation(net, act_type="relu", name="relu2")
    net = mx.sym.FullyConnected(net, num_hidden=10, name="fc3")
    return mx.sym.SoftmaxOutput(net, mx.sym.Variable("softmax_label"),
                                name="softmax")


def lenet():
    data = mx.sym.Variable("data")
    net = mx.sym.Convolution(data, kernel=(5, 5), num_filter=20, name="conv1")
    net = mx.sym.Activation(net, act_type="tanh", name="tanh1")
    net = mx.sym.Pooling(net, pool_type="max", kernel=(2, 2), stride=(2, 2),
                         name="pool1")
    net = mx.sym.Convolution(net, kernel=(5, 5), num_filter=50, name="conv2")
    net = mx.sym.Activation(net, act_type="tanh", name="tanh2")
    net = mx.sym.Pooling(net, pool_type="max", kernel=(2, 2), stride=(2, 2),
                         name="pool2")
    net = mx.sym.Flatten(net, name="flatten")
    net = mx.sym.FullyConnected(net, num_hidden=500, name="fc1")
    net = mx.sym.Activation(net, act_type="tanh", name="tanh3")
    net = mx.sym.FullyConnected(net, num_hidden=10, name="fc2")
    return mx.sym.SoftmaxOutput(net, mx.sym.Variable("softmax_label"),
                                name="softmax")


def get_iters(batch_size: int, flat: bool, data_dir: str, n: int):
    img = os.path.join(data_dir, "train-images-idx3-ubyte")
    lab = os.path.join(data_dir, "train-labels-idx1-ubyte")
    if os.path.exists(img) and os.path.exists(lab):
        return (mio.MNISTIter(img, lab, batch_size=batch_size, flat=flat,
                              shuffle=True),
                None)
    # Synthetic stand-in: 10 gaussian blobs in pixel space — learnable by
    # both networks, zero external dependencies.
    rng = onp.random.RandomState(0)
    protos = rng.rand(10, 28 * 28).astype("float32")
    y = rng.randint(0, 10, n)
    x = protos[y] + 0.15 * rng.randn(n, 28 * 28).astype("float32")
    x = x if flat else x.reshape(n, 1, 28, 28)
    split = int(0.9 * n)
    train = mio.NDArrayIter(x[:split], y[:split].astype("float32"),
                            batch_size=batch_size, shuffle=True)
    val = mio.NDArrayIter(x[split:], y[split:].astype("float32"),
                          batch_size=batch_size)
    return train, val


def main(argv=None) -> float:
    ap = argparse.ArgumentParser()
    ap.add_argument("--network", choices=("mlp", "lenet"), default="mlp")
    ap.add_argument("--num-epochs", type=int, default=5)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--lr", type=float, default=0.1)
    ap.add_argument("--data-dir", default="data/mnist")
    ap.add_argument("--num-synthetic", type=int, default=2000)
    ap.add_argument("--seed", type=int, default=None,
                    help="RNG seed; default: MXNET_TEST_SEED or 42")
    args = ap.parse_args(argv)

    # deterministic init (reference train.py seeds) — MXNET_TEST_SEED wins
    # so the committed seed-sweep actually varies the init across runs
    mx.random.seed(args.seed if args.seed is not None
                   else int(os.environ.get("MXNET_TEST_SEED", "42")))

    flat = args.network == "mlp"
    train, val = get_iters(args.batch_size, flat, args.data_dir,
                           args.num_synthetic)
    sym = mlp() if flat else lenet()
    mod = mx.module.Module(sym, data_names=("data",),
                           label_names=("softmax_label",))
    mod.fit(train, eval_data=val, num_epoch=args.num_epochs,
            optimizer="sgd",
            optimizer_params=(("learning_rate", args.lr), ("momentum", 0.9)),
            batch_end_callback=mx.callback.Speedometer(args.batch_size, 20))
    metric = mx.metric.Accuracy()
    res = mod.score(val if val is not None else train, metric)
    acc = dict(res)["accuracy"]
    print(f"final accuracy: {acc:.4f}")
    return acc


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Faster-RCNN end-to-end detection training on a synthetic shapes dataset.

Reference counterpart: GluonCV ``scripts/detection/faster_rcnn/
train_faster_rcnn.py`` (SURVEY §2.9, BASELINE.json configs[4] names
Faster-RCNN alongside SSD). The pipeline is the full two-stage recipe —
RPN over shifted anchors (``MultiProposal``), AnchorTarget/ProposalTarget
matching (``rpn_target``/``proposal_target``), four-way loss
(:class:`FasterRCNNTargetLoss`), ROIAlign head, per-class decode + NMS
(``FasterRCNN.detect``) — on the same offline shapes dataset the SSD
recipe uses: one axis-aligned bright rectangle per image, class = which
RGB channel is lit. Reports the same mAP proxy: the fraction of held-out
images whose top detection has the right class and IoU > 0.5.

    python examples/train_frcnn.py [--steps N] [--image-size 48]
"""
import argparse
import os
import sys

import numpy as onp

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
import incubator_mxnet_tpu as mx  # noqa: E402
from incubator_mxnet_tpu import gluon, models, nd  # noqa: E402


def make_dataset(rng, n, size):
    """(images (n, 3, S, S), labels (n, 1, 5) PIXEL coords): one colored
    rectangle on a dim noisy background; class = color channel."""
    imgs = 0.1 * rng.rand(n, 3, size, size).astype("float32")
    labels = onp.zeros((n, 1, 5), "float32")
    for i in range(n):
        cls = rng.randint(0, 3)
        w = rng.randint(size // 3, size // 2 + 1)
        h = rng.randint(size // 3, size // 2 + 1)
        x0 = rng.randint(0, size - w)
        y0 = rng.randint(0, size - h)
        imgs[i, cls, y0:y0 + h, x0:x0 + w] = 1.0
        labels[i, 0] = [cls, x0, y0, x0 + w - 1, y0 + h - 1]
    return imgs, labels


def _iou(a, b):
    ix = max(0.0, min(a[2], b[2]) - max(a[0], b[0]))
    iy = max(0.0, min(a[3], b[3]) - max(a[1], b[1]))
    inter = ix * iy
    ua = (a[2] - a[0]) * (a[3] - a[1]) + (b[2] - b[0]) * (b[3] - b[1]) - inter
    return inter / ua if ua > 0 else 0.0


def evaluate(net, imgs, labels, size, batch_size=16):
    """mAP proxy: top-detection hit rate (class right, IoU > 0.5)."""
    hits, total = 0, 0
    for s in range(0, len(imgs), batch_size):
        x = nd.array(imgs[s:s + batch_size])
        info = nd.array(onp.tile([size, size, 1.0],
                                 (x.shape[0], 1)).astype("float32"))
        det = net.detect(x, info, threshold=0.01).asnumpy()  # (B, N, 6)
        for b in range(det.shape[0]):
            rows = det[b]
            rows = rows[rows[:, 0] >= 0]
            total += 1
            if rows.size == 0:
                continue
            best = rows[rows[:, 1].argmax()]
            truth = labels[s + b, 0]
            if int(best[0]) == int(truth[0]) and \
                    _iou(best[2:6], truth[1:5]) > 0.5:
                hits += 1
    return hits / max(total, 1)


def main(argv=None) -> float:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=300)
    ap.add_argument("--batch-size", type=int, default=8)
    ap.add_argument("--image-size", type=int, default=48)
    ap.add_argument("--train-size", type=int, default=192)
    ap.add_argument("--val-size", type=int, default=64)
    ap.add_argument("--lr", type=float, default=0.003)
    ap.add_argument("--momentum", type=float, default=0.9)
    ap.add_argument("--ckpt-dir", default=None,
                    help="mx.fault checkpoint directory (atomic periodic "
                         "checkpoints; kill-safe)")
    ap.add_argument("--seed", type=int, default=None,
                    help="RNG seed; default: MXNET_TEST_SEED or 42")
    args = ap.parse_args(argv)

    mx.random.seed(args.seed if args.seed is not None
                   else int(os.environ.get("MXNET_TEST_SEED", "42")))
    rng = onp.random.RandomState(0)   # the dataset itself stays fixed
    tr_x, tr_y = make_dataset(rng, args.train_size, args.image_size)
    va_x, va_y = make_dataset(rng, args.val_size, args.image_size)

    # stride-4 trunk: anchors land on a 4px grid, so the 16-24px objects
    # reach RPN fg IoU without relying on forced matches alone
    net = models.FasterRCNN(
        num_classes=3, scales=(4, 6, 8), ratios=(0.5, 1, 2),
        feature_stride=4, rpn_pre_nms_top_n=128, rpn_post_nms_top_n=24,
        rpn_min_size=2, backbone_filters=(24, 48), output_rpn=True)
    net.initialize(mx.init.Xavier())
    net.hybridize()
    loss_fn = models.FasterRCNNTargetLoss(
        num_classes=3, scales=(4, 6, 8), ratios=(0.5, 1, 2),
        feature_stride=4, rpn_fg_overlap=0.5, head_fg_overlap=0.4)
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": args.lr,
                             "momentum": args.momentum})

    B = args.batch_size
    S = args.image_size
    info = nd.array(onp.tile([S, S, 1.0], (B, 1)).astype("float32"))
    for step in range(args.steps):
        idx = rng.randint(0, args.train_size, B)
        x, y = nd.array(tr_x[idx]), nd.array(tr_y[idx])
        with mx.autograd.record():
            # gt is appended to the roi set in training (reference
            # proposal_target.py) so the head always sees positives
            cls, box, rois, rpn_cls, rpn_reg = net(x, info, y)
            loss = loss_fn(cls, box, rois, rpn_cls, rpn_reg, y, info)
        loss.backward()
        trainer.step(1)   # the loss block already normalizes per stage
        if step % 50 == 0:
            if args.ckpt_dir:
                trainer.save_checkpoint(args.ckpt_dir)
            print(f"step {step:4d} loss {float(loss.asnumpy()):.4f}")

    acc = evaluate(net, va_x, va_y, S)
    print(f"detection accuracy (mAP proxy): {acc:.4f}")
    return acc


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Gluon image classification on the vision model zoo.

Reference counterpart: GluonCV
``scripts/classification/imagenet/train_imagenet.py`` shape (SURVEY §2.9),
scaled to run anywhere: any zoo model by name, hybridized to one XLA
program, bf16 AMP optional, kvstore-backed Trainer. Synthesizes a small
labeled set when no RecordIO file is given.

    python examples/image_classification.py --model resnet18_v1 --epochs 3
    python examples/image_classification.py --model mobilenet0.25 --amp
    python examples/image_classification.py --rec data/train.rec ...
"""
import argparse
import os
import sys

import numpy as onp

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
import incubator_mxnet_tpu as mx  # noqa: E402
from incubator_mxnet_tpu import gluon, io as mio  # noqa: E402
from incubator_mxnet_tpu.gluon.model_zoo import vision  # noqa: E402


def synthetic_iter(batch_size, classes, size, n=512):
    rng = onp.random.RandomState(0)
    protos = rng.rand(classes, 3, size, size).astype("float32")
    y = rng.randint(0, classes, n)
    x = protos[y] + 0.1 * rng.randn(n, 3, size, size).astype("float32")
    return mio.NDArrayIter(x, y.astype("float32"), batch_size=batch_size,
                           shuffle=True)


def main(argv=None) -> float:
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="resnet18_v1")
    ap.add_argument("--classes", type=int, default=8)
    ap.add_argument("--image-size", type=int, default=32)
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--lr", type=float, default=0.05)
    ap.add_argument("--rec", default=None, help="RecordIO file (ImageRecordIter)")
    ap.add_argument("--amp", action="store_true", help="bf16 mixed precision")
    ap.add_argument("--kvstore", default="device")
    ap.add_argument("--ckpt-dir", default=None,
                    help="mx.fault checkpoint directory (atomic per-epoch "
                         "checkpoints; kill-safe)")
    ap.add_argument("--seed", type=int, default=None,
                    help="RNG seed; default: MXNET_TEST_SEED or 42")
    args = ap.parse_args(argv)

    # deterministic init (reference train.py seeds) — MXNET_TEST_SEED wins
    # so the committed seed-sweep actually varies the init across runs
    mx.random.seed(args.seed if args.seed is not None
                   else int(os.environ.get("MXNET_TEST_SEED", "42")))

    if args.amp:
        from incubator_mxnet_tpu import amp
        amp.init()

    kwargs = {"classes": args.classes}
    if args.model.startswith("resnet"):
        kwargs["thumbnail"] = args.image_size < 64
    net = vision.get_model(args.model, **kwargs)
    net.initialize(mx.init.Xavier(magnitude=2.0))
    net.hybridize()

    if args.rec:
        it = mio.ImageRecordIter(
            path_imgrec=args.rec, batch_size=args.batch_size,
            data_shape=(3, args.image_size, args.image_size), shuffle=True)
    else:
        it = synthetic_iter(args.batch_size, args.classes, args.image_size)

    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": args.lr, "momentum": 0.9},
                            kvstore=args.kvstore)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    metric = mx.metric.Accuracy()
    for epoch in range(args.epochs):
        it.reset()
        metric.reset()
        for batch in it:
            with mx.autograd.record():
                out = net(batch.data[0])
                loss = loss_fn(out, batch.label[0])
            loss.backward()
            trainer.step(args.batch_size)
            metric.update(batch.label[0], out)
        if args.ckpt_dir:
            trainer.save_checkpoint(args.ckpt_dir)
        name, acc = metric.get()
        print(f"epoch {epoch}: {name}={acc:.4f}")
    return acc


if __name__ == "__main__":
    main()

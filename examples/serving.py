"""Serve a LeNet through mx.serve end to end — the inference counterpart
of train_mnist.py.

Flow: build (or checkpoint-restore) the model → export a bucketed serving
artifact (one StableHLO per shape bucket) → cold-load it into a
ModelRegistry (no Python model class needed at serving time) → warm every
bucket → push mixed-size requests through the DynamicBatcher → print the
latency/occupancy/compile-counter report as JSON.

    python examples/serving.py --requests 200
    python examples/serving.py --ckpt-dir ckpts/   # newest verified weights

The exit code enforces the serving contract: zero post-warmup recompiles.
"""
import argparse
import json
import os
import sys
import tempfile

import numpy as onp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import incubator_mxnet_tpu as mx  # noqa: E402
from incubator_mxnet_tpu import models, nd, serve  # noqa: E402


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--requests", type=int, default=200)
    ap.add_argument("--max-batch", type=int, default=8)
    ap.add_argument("--deadline-ms", type=float, default=5.0)
    ap.add_argument("--ckpt-dir", default=None,
                    help="restore weights from the newest verified "
                         "fault checkpoint under this directory")
    ap.add_argument("--export-dir", default=None,
                    help="where the serving artifact lands "
                         "(default: a temp dir)")
    args = ap.parse_args(argv)

    # 1. a model with one recorded forward (training would go here)
    net = models.LeNet()
    net.initialize()
    net.hybridize()
    x = nd.array(onp.zeros((2, 1, 28, 28), "float32"))
    net(x)
    net(x)

    # 2. export one compiled graph per shape bucket
    table = serve.BucketTable({"batch": (1, args.max_batch)})
    spec = models.serve_spec("lenet")
    export_dir = args.export_dir or tempfile.mkdtemp(prefix="mx-serve-")
    prefix = os.path.join(export_dir, "lenet")
    serve.export_for_serving(net, prefix, table, spec["input_axes"])

    # 3. cold-load into the registry (artifact + optional newer weights)
    reg = serve.ModelRegistry()
    reg.load("lenet", table=table, input_axes=spec["input_axes"],
             output_axes=spec["output_axes"], artifacts=prefix,
             ckpt_root=args.ckpt_dir)
    model = reg.get("lenet")

    # 4. serve mixed-size requests through the batcher
    batcher = serve.DynamicBatcher(model, max_delay_ms=args.deadline_ms,
                                   max_batch=args.max_batch).start()
    rng = onp.random.RandomState(0)
    futures = [batcher.submit(rng.randn(1, 28, 28).astype("float32"))
               for _ in range(args.requests)]
    preds = [int(onp.asarray(f.result(timeout=60)).argmax())
             for f in futures]
    snapshot = batcher.metrics.snapshot(model)
    batcher.stop()

    print(json.dumps({"served": len(preds),
                      "class_histogram": onp.bincount(
                          onp.asarray(preds), minlength=10).tolist(),
                      "metrics": snapshot}, indent=1))
    recompiles = snapshot["compile_cache"]["post_warmup_compiles"]
    if recompiles:
        print(f"serving contract violated: {recompiles} post-warmup "
              "recompile(s)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())

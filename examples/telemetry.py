"""One observability spine, end to end — train a few steps, serve a
burst, and read everything back through mx.telemetry.

Flow: a small MLP trains under a guarded ShardedTrainer (step events with
wall/place/dispatch timings, loss and grad-norm), checkpoints, then the
same net is bucket-compiled and serves a mixed batch burst through the
DynamicBatcher (admit/batch/execute/reply events with request ids). The
whole run lands in:

- a **JSON-lines event stream** (``--jsonl``, strict JSON, one event per
  line, step/request correlation ids);
- a **Prometheus text scrape** (``--prom``) with counters from BOTH
  training (``mxtpu_train_*``) and serving (``mxtpu_serve_*``);
- the **compile ledger** — every XLA compile with signature/wall-time/
  call-site, and zero post-warmup compiles asserted;
- ``telemetry.snapshot()`` — the "what is this job doing right now" dict
  printed at the end.

    python examples/telemetry.py --steps 5 --requests 40
    python examples/telemetry.py --jsonl /tmp/events.jsonl --trace /tmp/t.json

The exit code enforces the ledger contract: zero post-warmup compiles
across trainer AND serving.
"""
import argparse
import json
import os
import sys
import tempfile

import numpy as onp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import incubator_mxnet_tpu as mx  # noqa: E402
from incubator_mxnet_tpu import (  # noqa: E402
    fault, gluon, nd, parallel, serve, telemetry,
)

IN, HIDDEN, CLASSES = 32, 64, 8


def build_net():
    net = gluon.nn.HybridSequential(prefix="tele_")
    with net.name_scope():
        net.add(gluon.nn.Dense(HIDDEN, activation="relu", in_units=IN))
        net.add(gluon.nn.Dense(CLASSES, in_units=HIDDEN))
    net.initialize()
    return net


def train(net, steps: int, batch: int, ckpt_dir: str):
    """A short guarded training loop — every step publishes a
    ``train.step`` event and the step histogram/counters."""
    guard = fault.StepGuard(policy="warn")
    trainer = parallel.ShardedTrainer(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
        {"learning_rate": 0.05}, guard=guard,
        watchdog=fault.Watchdog(deadline=120.0))
    rng = onp.random.RandomState(0)
    for _ in range(steps):
        x = rng.randn(batch, IN).astype("float32")
        y = (x.sum(axis=1) > 0).astype("int32") % CLASSES
        trainer.step(x, y)
    trainer.save_checkpoint(ckpt_dir, keep=2)
    trainer.sync_to_block()
    return trainer


def serve_burst(net, requests: int, max_batch: int):
    """A batched serve burst over the trained weights — every request
    rides admit → batch → execute → reply events with its request id."""
    net.hybridize()
    net(nd.array(onp.zeros((2, IN), "float32")))
    table = serve.BucketTable({"batch": (1, max_batch)})
    model = serve.CompiledModel(net, table, [{0: "batch"}],
                                output_axes=[{0: "batch"}])
    model.warmup()
    batcher = serve.DynamicBatcher(model, max_delay_ms=2.0,
                                   max_batch=max_batch).start()
    rng = onp.random.RandomState(1)
    futures = [batcher.submit(rng.randn(IN).astype("float32"))
               for _ in range(requests)]
    for f in futures:
        f.result(timeout=60)
    snap = batcher.metrics.snapshot(model)
    batcher.stop()
    return snap


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--requests", type=int, default=40)
    ap.add_argument("--max-batch", type=int, default=8)
    ap.add_argument("--ckpt-dir", default=None)
    ap.add_argument("--jsonl", default=None,
                    help="event-stream path (default: a temp file)")
    ap.add_argument("--prom", default=None,
                    help="also write the Prometheus scrape here")
    ap.add_argument("--trace", default=None,
                    help="also write the merged chrome://tracing JSON")
    args = ap.parse_args(argv)

    workdir = tempfile.mkdtemp(prefix="mx-telemetry-")
    jsonl = args.jsonl or os.path.join(workdir, "events.jsonl")
    sink = telemetry.install_jsonl(jsonl)

    net = build_net()
    trainer = train(net, args.steps, args.batch,
                    args.ckpt_dir or os.path.join(workdir, "ckpts"))
    serve_snap = serve_burst(net, args.requests, args.max_batch)

    prom = telemetry.prometheus_text()
    if args.prom:
        with open(args.prom, "w") as f:
            f.write(prom)
    if args.trace:
        with open(args.trace, "w") as f:
            f.write(telemetry.chrome_trace())

    snapshot = telemetry.snapshot()
    ledger = snapshot["compiles"]
    print(json.dumps({
        "jsonl": jsonl,
        "jsonl_lines": sink.lines,
        "event_counts": snapshot["events"]["counts"],
        "compile_ledger": ledger,
        "train_last_loss": trainer.last_loss,
        "serve": {k: serve_snap[k] for k in ("requests", "batches",
                                             "latency")},
    }, indent=1, sort_keys=True))

    post_warmup = ledger["post_warmup"]
    if post_warmup:
        print(f"telemetry contract violated: {post_warmup} post-warmup "
              "compile(s) across trainer+serve", file=sys.stderr)
        return 1
    assert "mxtpu_train_steps_total" in prom
    assert "mxtpu_serve_requests_total" in prom
    return 0


if __name__ == "__main__":
    sys.exit(main())

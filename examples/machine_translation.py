#!/usr/bin/env python
"""Transformer NMT: training + beam-search inference.

Reference counterpart: GluonNLP ``scripts/machine_translation/`` (the
Transformer-big WMT recipe in BASELINE.json, SURVEY §2.9), scaled to run
anywhere: trains a small Transformer encoder-decoder on a synthetic
copy/reverse task (no network access) with teacher forcing and label
smoothing, then decodes with the static-shape beam search and reports
exact-match accuracy.

    python examples/machine_translation.py --task reverse --steps 300
"""
import argparse
import os
import sys

import numpy as onp

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
import incubator_mxnet_tpu as mx  # noqa: E402
from incubator_mxnet_tpu import gluon, nd  # noqa: E402
from incubator_mxnet_tpu.models import NMTModel, beam_search  # noqa: E402

BOS, EOS, PAD = 1, 2, 0


def make_batch(rng, batch_size, seq_len, vocab, task):
    src = rng.randint(3, vocab, (batch_size, seq_len)).astype("int32")
    tgt_core = src[:, ::-1] if task == "reverse" else src
    # decoder input: BOS + core; label: core + EOS (teacher forcing shift)
    tgt_in = onp.concatenate(
        [onp.full((batch_size, 1), BOS, "int32"), tgt_core], axis=1)
    label = onp.concatenate(
        [tgt_core, onp.full((batch_size, 1), EOS, "int32")], axis=1)
    return src, tgt_in, label


def main(argv=None) -> float:
    ap = argparse.ArgumentParser()
    ap.add_argument("--task", choices=("copy", "reverse"), default="reverse")
    ap.add_argument("--vocab", type=int, default=32)
    ap.add_argument("--seq-len", type=int, default=8)
    ap.add_argument("--steps", type=int, default=300)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--lr", type=float, default=3e-3)
    ap.add_argument("--beam-size", type=int, default=4)
    ap.add_argument("--smooth-eps", type=float, default=0.1,
                    help="label-smoothing epsilon (0 disables)")
    ap.add_argument("--ckpt-dir", default=None,
                    help="mx.fault checkpoint directory (atomic periodic "
                         "checkpoints; kill-safe)")
    ap.add_argument("--seed", type=int, default=None,
                    help="RNG seed; default: MXNET_TEST_SEED or 42")
    args = ap.parse_args(argv)

    # deterministic init (reference train.py seeds) — MXNET_TEST_SEED wins
    # so the committed seed-sweep actually varies the init across runs
    mx.random.seed(args.seed if args.seed is not None
                   else int(os.environ.get("MXNET_TEST_SEED", "42")))

    net = NMTModel(src_vocab=args.vocab, tgt_vocab=args.vocab, units=64,
                   hidden_size=128, num_layers=2, num_heads=4, dropout=0.0,
                   max_length=args.seq_len + 2)
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})
    # label smoothing as in the GluonNLP recipe: sparse targets become
    # (1-eps)*one_hot + eps/V dense distributions fed to dense-label CE
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss(sparse_label=False)
    rng = onp.random.RandomState(0)
    for step in range(args.steps):
        src, tgt_in, label = make_batch(rng, args.batch_size, args.seq_len,
                                        args.vocab, args.task)
        smoothed = onp.full((label.size, args.vocab),
                            args.smooth_eps / args.vocab, "float32")
        smoothed[onp.arange(label.size), label.reshape(-1)] += \
            1.0 - args.smooth_eps
        with mx.autograd.record():
            logits = net(nd.array(src), nd.array(tgt_in))
            loss = loss_fn(logits.reshape((-1, args.vocab)),
                           nd.array(smoothed))
        loss.backward()
        trainer.step(args.batch_size)
        if args.ckpt_dir and (step % 50 == 0 or step == args.steps - 1):
            trainer.save_checkpoint(args.ckpt_dir)
        if step % 50 == 0 or step == args.steps - 1:
            print(f"step {step:4d}  loss {float(loss.asnumpy().mean()):.4f}")

    # beam-search decode a held-out batch; exact sequence match
    src, _, label = make_batch(rng, 16, args.seq_len, args.vocab, args.task)
    seqs, scores = beam_search(net, nd.array(src), beam_size=args.beam_size,
                               max_length=args.seq_len + 1, bos_id=BOS,
                               eos_id=EOS)
    # sequences exclude BOS: positions [0, seq_len) are the decoded core
    best = onp.asarray(seqs)[:, 0, :args.seq_len]
    target = label[:, :args.seq_len]
    acc = float((best == target).all(axis=1).mean())
    print(f"beam-search exact-match: {acc:.2f}")
    return acc


if __name__ == "__main__":
    main()
